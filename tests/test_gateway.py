"""Gateway HTTP tests (httptest equivalent): jobs, approvals, workflows,
runs, DLQ, policy, config, schemas, locks, artifacts, traces, status, WS."""
import asyncio
import json

import pytest
from aiohttp.test_utils import TestClient, TestServer

from cordum_tpu.controlplane.gateway.app import Gateway
from cordum_tpu.controlplane.gateway.auth import BasicAuthProvider, TokenBucket
from cordum_tpu.controlplane.safetykernel.kernel import SafetyKernel
from cordum_tpu.controlplane.scheduler.engine import Engine as Scheduler
from cordum_tpu.controlplane.scheduler.safety_client import SafetyClient
from cordum_tpu.controlplane.scheduler.strategy import LeastLoadedStrategy
from cordum_tpu.controlplane.workflowengine.service import WorkflowEngineService
from cordum_tpu.infra.bus import LoopbackBus
from cordum_tpu.infra.config import parse_pool_config
from cordum_tpu.infra.configsvc import ConfigService
from cordum_tpu.infra.jobstore import JobStore
from cordum_tpu.infra.kv import MemoryKV
from cordum_tpu.infra.memstore import MemoryStore
from cordum_tpu.infra.registry import WorkerRegistry
from cordum_tpu.infra.schemareg import SchemaRegistry
from cordum_tpu.protocol import subjects as subj
from cordum_tpu.workflow.engine import Engine as WorkflowEngine
from cordum_tpu.workflow.store import WorkflowStore
from cordum_tpu.worker.runtime import JobContext, Worker

POLICY = {
    "default_tenant": "default",
    "tenants": {"default": {"allow_topics": ["job.*", "job.>"]}},
    "rules": [
        {"id": "approve-deploy", "match": {"topics": ["job.deploy.*"]}, "decision": "require_approval",
         "remediations": [{"id": "use-staging", "replacement_topic": "job.work",
                           "add_labels": {"env": "staging"}}]},
    ],
}


class GwStack:
    """Full control plane behind a live HTTP server."""

    def __init__(self):
        self.kv = MemoryKV()
        self.bus = LoopbackBus()
        self.job_store = JobStore(self.kv)
        self.mem = MemoryStore(self.kv)
        self.wf_store = WorkflowStore(self.kv)
        self.schemas = SchemaRegistry(self.kv)
        self.configsvc = ConfigService(self.kv)
        self.kernel = SafetyKernel(policy_doc=POLICY, configsvc=self.configsvc)
        self.registry = WorkerRegistry()
        pc = parse_pool_config({"topics": {"job.work": "p"}, "pools": {"p": {}}})
        self.scheduler = Scheduler(
            bus=self.bus, job_store=self.job_store, safety=SafetyClient(self.kernel.check),
            strategy=LeastLoadedStrategy(self.registry, pc), registry=self.registry,
        )
        self.wf_engine = WorkflowEngine(store=self.wf_store, bus=self.bus, mem=self.mem,
                                        schemas=self.schemas, configsvc=self.configsvc)
        self.wf_service = WorkflowEngineService(engine=self.wf_engine, bus=self.bus,
                                                job_store=self.job_store, reconcile_interval_s=0.1)
        self.gw = Gateway(
            kv=self.kv, bus=self.bus, job_store=self.job_store, mem=self.mem,
            kernel=self.kernel, wf_store=self.wf_store, wf_engine=self.wf_engine,
            schemas=self.schemas, configsvc=self.configsvc, registry=self.registry,
            auth=BasicAuthProvider(["user-key"], admin_keys=["admin-key"]),
        )
        self.worker = Worker(bus=self.bus, store=self.mem, worker_id="w1", pool="p",
                             topics=["job.work"], heartbeat_interval_s=999)
        self.client: TestClient = None

    async def __aenter__(self):
        async def handler(ctx: JobContext):
            p = ctx.payload if isinstance(ctx.payload, dict) else {}
            if p.get("fail"):
                raise RuntimeError("worker failure requested")
            return {"done": True, "echo": p}

        self.worker.register("job.work", handler)
        await self.kernel.reload()
        await self.scheduler.start()
        await self.wf_service.start()
        await self.worker.start()
        # bus taps only (no TCP listen needed for TestServer)
        self.gw._subs.append(await self.bus.subscribe(subj.DLQ, self.gw._tap_dlq))
        self.gw._subs.append(await self.bus.subscribe("sys.job.>", self.gw._tap_events))
        self.client = TestClient(TestServer(self.gw.app))
        await self.client.start_server()
        return self

    async def __aexit__(self, *exc):
        await self.client.close()
        await self.worker.stop()
        await self.wf_service.stop()
        await self.scheduler.stop()
        for s in self.gw._subs:
            s.unsubscribe()
        await self.bus.close()

    async def settle(self, rounds=10):
        for _ in range(rounds):
            await self.bus.drain()
            await asyncio.sleep(0.01)

    def h(self, admin=False, **extra):
        return {"X-Api-Key": "admin-key" if admin else "user-key", **extra}


async def test_auth_required():
    async with GwStack() as s:
        r = await s.client.get("/api/v1/jobs")
        assert r.status == 401
        r = await s.client.get("/api/v1/jobs", headers=s.h())
        assert r.status == 200


async def test_tenant_scope_enforced():
    """A keyholder may not pick an arbitrary tenant via header or body
    (reference ResolveTenant/RequireTenantAccess, basic_auth.go:100-122)."""
    async with GwStack() as s:
        # header tenant outside the key's scope → auth rejected
        r = await s.client.get("/api/v1/jobs", headers=s.h(**{"X-Tenant-Id": "other"}))
        assert r.status == 401
        # body tenant_id outside the principal's tenant → 403
        r = await s.client.post(
            "/api/v1/jobs",
            json={"topic": "job.work", "tenant_id": "other"},
            headers=s.h(),
        )
        assert r.status == 403
        # admins may act across tenants; default tenant always fine
        r = await s.client.post(
            "/api/v1/jobs",
            json={"topic": "job.work", "tenant_id": "other"},
            headers=s.h(admin=True),
        )
        assert r.status == 202
        r = await s.client.post(
            "/api/v1/jobs",
            json={"topic": "job.work", "tenant_id": "default"},
            headers=s.h(),
        )
        assert r.status == 202


def test_key_tenant_map_allows_assigned_tenant():
    prov = BasicAuthProvider(
        ["k1", "k2"], key_tenants={"k2": "acme"}, default_tenant="default"
    )
    # k2 is scoped to acme: may select it, lands in it by default assignment
    p = prov.authenticate({"X-Api-Key": "k2", "X-Tenant-Id": "acme"})
    assert p is not None and p.tenant_id == "acme"
    assert prov.authenticate({"X-Api-Key": "k2"}).tenant_id == "acme"
    # k1 has no assignment → cannot select acme
    assert prov.authenticate({"X-Api-Key": "k1", "X-Tenant-Id": "acme"}) is None
    assert prov.authenticate({"X-Api-Key": "k1"}).tenant_id == "default"


async def test_job_submit_roundtrip():
    async with GwStack() as s:
        r = await s.client.post("/api/v1/jobs", json={"topic": "job.work", "payload": {"n": 1}},
                                headers=s.h())
        assert r.status == 202
        jid = (await r.json())["job_id"]
        await s.settle()
        r = await s.client.get(f"/api/v1/jobs/{jid}?events=true&result=true", headers=s.h())
        doc = await r.json()
        assert doc["state"] == "SUCCEEDED"
        assert doc["result"] == {"done": True, "echo": {"n": 1}}
        assert any(e["event"] == "submit" for e in doc["events"])
        # trace reader
        r = await s.client.get(f"/api/v1/traces/{doc['trace_id']}", headers=s.h())
        tr = await r.json()
        assert tr["jobs"][0]["job_id"] == jid


async def test_job_submit_validation_and_idempotency():
    async with GwStack() as s:
        r = await s.client.post("/api/v1/jobs", json={"payload": {}}, headers=s.h())
        assert r.status == 400
        r = await s.client.post("/api/v1/jobs", data=b"not json", headers=s.h())
        assert r.status == 400
        r1 = await s.client.post("/api/v1/jobs", json={"topic": "job.work", "idempotency_key": "k1"},
                                 headers=s.h())
        r2 = await s.client.post("/api/v1/jobs", json={"topic": "job.work", "idempotency_key": "k1"},
                                 headers=s.h())
        j1, j2 = (await r1.json()), (await r2.json())
        assert j1["job_id"] == j2["job_id"] and j2.get("deduplicated")


async def test_bulk_submit_roundtrip():
    """POST /api/v1/jobs:batch: one round trip, per-job verdicts, bad jobs
    isolated, batchable payloads stamped with the batch-key label."""
    async with GwStack() as s:
        r = await s.client.post("/api/v1/jobs:batch", json={"jobs": [
            {"topic": "job.work", "payload": {"n": 0}},
            {"topic": "job.work", "payload": {"op": "embed", "texts": ["hi"]}},
            {"payload": {"missing": "topic"}},
        ]}, headers=s.h())
        assert r.status == 202
        doc = await r.json()
        assert doc["accepted"] == 2 and doc["rejected"] == 1
        assert doc["jobs"][2]["status"] == 400 and "topic" in doc["jobs"][2]["error"]
        await s.settle()
        for entry in doc["jobs"][:2]:
            meta = await s.job_store.get_meta(entry["job_id"])
            assert meta["state"] == "SUCCEEDED", meta
        # the embed job carries the batch-routing label for affinity
        req = await s.job_store.get_request(doc["jobs"][1]["job_id"])
        assert req.labels.get("cordum.batch_key") == "embed"
        req0 = await s.job_store.get_request(doc["jobs"][0]["job_id"])
        assert "cordum.batch_key" not in (req0.labels or {})


async def test_bulk_submit_validation():
    async with GwStack() as s:
        r = await s.client.post("/api/v1/jobs:batch", json={"jobs": []}, headers=s.h())
        assert r.status == 400
        r = await s.client.post("/api/v1/jobs:batch", json={}, headers=s.h())
        assert r.status == 400
        # every job rejected → 400, verdicts still positional
        r = await s.client.post("/api/v1/jobs:batch",
                                json={"jobs": [{"payload": {}}, "not-a-dict"]},
                                headers=s.h())
        assert r.status == 400
        doc = await r.json()
        assert doc["accepted"] == 0 and len(doc["jobs"]) == 2
        from cordum_tpu.controlplane.gateway.app import MAX_BULK_JOBS

        too_many = [{"topic": "job.work"}] * (MAX_BULK_JOBS + 1)
        r = await s.client.post("/api/v1/jobs:batch", json={"jobs": too_many},
                                headers=s.h())
        assert r.status == 400


async def test_secret_detection_labels():
    async with GwStack() as s:
        r = await s.client.post(
            "/api/v1/jobs",
            json={"topic": "job.work", "payload": {"token": "secret://vault/x"}},
            headers=s.h(),
        )
        jid = (await r.json())["job_id"]
        req = await s.job_store.get_request(jid)
        assert req.labels.get("secrets_present") == "true"
        assert "secrets" in req.metadata.risk_tags


async def test_approval_flow_over_http():
    async with GwStack() as s:
        r = await s.client.post("/api/v1/jobs", json={"topic": "job.deploy.api", "payload": {}},
                                headers=s.h())
        jid = (await r.json())["job_id"]
        await s.settle()
        r = await s.client.get(f"/api/v1/jobs/{jid}", headers=s.h())
        assert (await r.json())["state"] == "APPROVAL_REQUIRED"
        r = await s.client.get("/api/v1/approvals", headers=s.h())
        approvals = (await r.json())["approvals"]
        assert any(a["job_id"] == jid for a in approvals)
        # non-admin cannot approve
        r = await s.client.post(f"/api/v1/approvals/{jid}/approve", headers=s.h())
        assert r.status == 403
        # admin approves; job dispatches (topic job.deploy.api has no pool;
        # falls back to topic subject, no worker → stays RUNNING)
        r = await s.client.post(f"/api/v1/approvals/{jid}/approve", headers=s.h(admin=True))
        assert r.status == 200
        await s.settle()
        r = await s.client.get(f"/api/v1/jobs/{jid}", headers=s.h())
        assert (await r.json())["state"] == "RUNNING"
        rec = await s.job_store.get_approval(jid)
        assert rec.approved and rec.approved_by == "anonymous"


async def test_reject_flow_over_http():
    async with GwStack() as s:
        r = await s.client.post("/api/v1/jobs", json={"topic": "job.deploy.x", "payload": {}},
                                headers=s.h())
        jid = (await r.json())["job_id"]
        await s.settle()
        r = await s.client.post(f"/api/v1/approvals/{jid}/reject", json={"reason": "too risky"},
                                headers=s.h(admin=True))
        assert r.status == 200
        r = await s.client.get(f"/api/v1/jobs/{jid}", headers=s.h())
        doc = await r.json()
        assert doc["state"] == "DENIED" and "too risky" in doc["deny_reason"]


async def test_remediation_applies():
    async with GwStack() as s:
        r = await s.client.post("/api/v1/jobs", json={"topic": "job.deploy.api", "payload": {"x": 1}},
                                headers=s.h())
        jid = (await r.json())["job_id"]
        await s.settle()
        r = await s.client.post(f"/api/v1/jobs/{jid}/remediate",
                                json={"remediation_id": "use-staging"}, headers=s.h())
        assert r.status == 202
        new_jid = (await r.json())["job_id"]
        await s.settle()
        r = await s.client.get(f"/api/v1/jobs/{new_jid}?result=true", headers=s.h())
        doc = await r.json()
        assert doc["state"] == "SUCCEEDED"  # remediated to job.work → worker ran it
        req = await s.job_store.get_request(new_jid)
        assert req.labels["env"] == "staging"


async def test_dlq_list_and_retry():
    async with GwStack() as s:
        r = await s.client.post("/api/v1/jobs", json={"topic": "job.work", "payload": {"fail": True}},
                                headers=s.h())
        jid = (await r.json())["job_id"]
        await s.settle()
        r = await s.client.get("/api/v1/dlq", headers=s.h())
        doc = await r.json()
        assert doc["total"] >= 1 and any(e["job_id"] == jid for e in doc["entries"])
        # retry under a new job id with a now-passing payload? payload is
        # rehydrated as-is, so it fails again — but the retry mechanics work
        r = await s.client.post(f"/api/v1/dlq/{jid}/retry", headers=s.h())
        assert r.status == 202
        new_jid = (await r.json())["job_id"]
        assert new_jid != jid
        await s.settle()
        meta = await s.job_store.get_meta(new_jid)
        assert meta["retried_from"] == jid
        r = await s.client.delete(f"/api/v1/dlq/{new_jid}", headers=s.h())


async def test_workflow_api_end_to_end():
    async with GwStack() as s:
        wf = {"id": "wf-http", "name": "t",
              "steps": {"a": {"topic": "job.work", "input": {"n": "${input.n}"}}}}
        r = await s.client.post("/api/v1/workflows", json=wf, headers=s.h())
        assert r.status == 201
        r = await s.client.get("/api/v1/workflows", headers=s.h())
        assert "wf-http" in (await r.json())["workflows"]
        r = await s.client.post("/api/v1/workflows/wf-http/runs", json={"input": {"n": 5}},
                                headers=s.h(), )
        assert r.status == 202
        run_id = (await r.json())["run_id"]
        for _ in range(50):
            await s.settle(rounds=2)
            r = await s.client.get(f"/api/v1/runs/{run_id}", headers=s.h())
            doc = await r.json()
            if doc["status"] in ("SUCCEEDED", "FAILED"):
                break
        assert doc["status"] == "SUCCEEDED"
        assert doc["context"]["steps"]["a"] == {"done": True, "echo": {"n": 5}}
        r = await s.client.get(f"/api/v1/runs/{run_id}/timeline", headers=s.h())
        assert any(e["event"] == "run_started" for e in (await r.json())["timeline"])


async def test_workflow_invalid_rejected():
    async with GwStack() as s:
        wf = {"id": "bad", "steps": {"a": {"topic": "t", "depends_on": ["zzz"]}}}
        r = await s.client.post("/api/v1/workflows", json=wf, headers=s.h())
        assert r.status == 400


async def test_run_idempotency_header():
    async with GwStack() as s:
        wf = {"id": "wf2", "steps": {"a": {"topic": "job.work"}}}
        await s.client.post("/api/v1/workflows", json=wf, headers=s.h())
        r1 = await s.client.post("/api/v1/workflows/wf2/runs", json={},
                                 headers=s.h(**{"Idempotency-Key": "run-1"}))
        r2 = await s.client.post("/api/v1/workflows/wf2/runs", json={},
                                 headers=s.h(**{"Idempotency-Key": "run-1"}))
        assert (await r1.json())["run_id"] == (await r2.json())["run_id"]


async def test_policy_admin_endpoints():
    async with GwStack() as s:
        r = await s.client.post("/api/v1/policy/evaluate",
                                json={"topic": "job.deploy.api"}, headers=s.h())
        assert (await r.json())["decision"] == "REQUIRE_APPROVAL"
        r = await s.client.post("/api/v1/policy/explain",
                                json={"topic": "job.deploy.api"}, headers=s.h())
        doc = await r.json()
        assert any(t["matched"] for t in doc["trail"])
        r = await s.client.post("/api/v1/policy/simulate", json={
            "policy": {"rules": [{"id": "d", "match": {"topics": ["job.*"]}, "decision": "deny"}]},
            "requests": [{"topic": "job.x"}],
        }, headers=s.h())
        assert (await r.json())["results"][0]["decision"] == "DENY"
        r = await s.client.get("/api/v1/policy/snapshots", headers=s.h())
        assert (await r.json())["current"]


async def test_config_endpoints_and_policy_fragment_reload():
    async with GwStack() as s:
        r = await s.client.put("/api/v1/config/system/default",
                               json={"data": {"models": {"default_model": "llama"}}}, headers=s.h())
        assert r.status == 403  # non-admin
        r = await s.client.put("/api/v1/config/system/default",
                               json={"data": {"models": {"default_model": "llama"}}},
                               headers=s.h(admin=True))
        assert r.status == 200
        r = await s.client.get("/api/v1/config/effective", headers=s.h())
        assert (await r.json())["effective"]["models"]["default_model"] == "llama"
        # installing a policy fragment via config triggers kernel reload
        snap_before = s.kernel.snapshot_id
        r = await s.client.put("/api/v1/config/system/policy/frag1",
                               json={"data": {"enabled": True,
                                              "rules": [{"id": "f", "match": {"topics": ["job.frag"]},
                                                         "decision": "deny"}]}},
                               headers=s.h(admin=True))
        assert r.status == 200
        assert s.kernel.snapshot_id != snap_before


async def test_schema_lock_artifact_memory_endpoints():
    async with GwStack() as s:
        r = await s.client.put("/api/v1/schemas/s1",
                               json={"type": "object", "required": ["x"]}, headers=s.h())
        assert r.status == 201
        r = await s.client.get("/api/v1/schemas/s1", headers=s.h())
        assert (await r.json())["required"] == ["x"]
        r = await s.client.post("/api/v1/locks/res1/acquire", json={"owner": "me"}, headers=s.h())
        assert (await r.json())["acquired"]
        r = await s.client.post("/api/v1/locks/res1/acquire", json={"owner": "you"}, headers=s.h())
        assert r.status == 409
        r = await s.client.get("/api/v1/locks", headers=s.h())
        assert len((await r.json())["locks"]) == 1
        r = await s.client.post("/api/v1/locks/res1/release", json={"owner": "me"}, headers=s.h())
        assert (await r.json())["released"]
        r = await s.client.post("/api/v1/artifacts?retention=short", data=b"blob", headers=s.h())
        aid = (await r.json())["artifact_id"]
        r = await s.client.get(f"/api/v1/artifacts/{aid}", headers=s.h())
        assert await r.read() == b"blob"
        # memory pointer reader
        ptr = await s.mem.put_context("jx", {"v": 1})
        r = await s.client.get(f"/api/v1/memory?ptr={ptr}", headers=s.h())
        assert (await r.json())["value"] == {"v": 1}


async def test_status_metrics_workers():
    async with GwStack() as s:
        await s.worker.send_heartbeat()
        await s.settle()
        r = await s.client.get("/api/v1/workers", headers=s.h())
        doc = await r.json()
        assert "w1" in doc["workers"]
        r = await s.client.get("/api/v1/status", headers=s.h())
        st = await r.json()
        assert st["bus"] and st["kv"] and st["policy_snapshot"]
        r = await s.client.get("/metrics", headers=s.h())
        text = await r.text()
        assert "cordum_http_requests_total" in text
        r = await s.client.get("/healthz")
        assert r.status == 200


async def test_ws_stream_broadcast():
    async with GwStack() as s:
        ws = await s.client.ws_connect("/api/v1/stream", headers=s.h())
        await s.client.post("/api/v1/jobs", json={"topic": "job.work", "payload": {}},
                            headers=s.h())
        msg = await asyncio.wait_for(ws.receive_json(), 5)
        assert msg["subject"].startswith("sys.job.")
        await ws.close()


async def test_ws_stream_key_via_subprotocol():
    """Browsers can't set WS headers: the API key rides the first
    Sec-WebSocket-Protocol token (reference gateway.go:2002) and the server
    echoes the offered protocol so the handshake completes."""
    async with GwStack() as s:
        ws = await s.client.ws_connect("/api/v1/stream", protocols=("user-key",))
        assert ws._response.headers.get("Sec-WebSocket-Protocol") == "user-key"
        await s.client.post("/api/v1/jobs", json={"topic": "job.work", "payload": {}},
                            headers=s.h())
        msg = await asyncio.wait_for(ws.receive_json(), 5)
        assert msg["subject"].startswith("sys.job.")
        await ws.close()
        # a bad key in the subprotocol is rejected
        from aiohttp import WSServerHandshakeError
        import pytest as _pytest
        with _pytest.raises(WSServerHandshakeError):
            await s.client.ws_connect("/api/v1/stream", protocols=("wrong-key",))


async def test_dashboard_served():
    """The ops dashboard (reference dashboard/ subsystem) is served by the
    gateway: / → SPA shell, /ui/* → assets, no API key required for statics."""
    async with GwStack() as s:
        r = await s.client.get("/")
        assert r.status == 200
        html = await r.text()
        assert "Cordum TPU" in html and "/ui/app.js" in html
        for asset in ("/ui/app.js", "/ui/style.css"):
            r = await s.client.get(asset)
            assert r.status == 200, asset
        js = await (await s.client.get("/ui/app.js")).text()
        # every nav page the SPA declares exists in the bundle
        for page in ("overview", "jobs", "approvals", "workflows", "runs",
                     "dlq", "workers", "policy", "packs", "config", "settings"):
            assert f"pages.{page}" in js, page


async def test_context_endpoints():
    from cordum_tpu.context.service import ContextService

    async with GwStack() as s:
        s.gw.context_svc = ContextService(s.kv)
        r = await s.client.post("/api/v1/context/memory/m1",
                                json={"payload": "hello", "model_response": "world"}, headers=s.h())
        assert r.status == 200
        r = await s.client.post("/api/v1/context/window",
                                json={"memory_id": "m1", "mode": "CHAT", "payload": "next"},
                                headers=s.h())
        doc = await r.json()
        roles = [m["content"] for m in doc["messages"]]
        assert roles == ["hello", "world", "next"]
        r = await s.client.put("/api/v1/context/chunks/m1",
                               json={"chunks": [{"file_path": "a", "content": "x"}]}, headers=s.h())
        assert r.status == 200


async def test_job_cancel_endpoint():
    async with GwStack() as s:
        # submit to a topic with no worker so it stays RUNNING
        r = await s.client.post("/api/v1/jobs", json={"topic": "job.nopool", "payload": {}},
                                headers=s.h())
        jid = (await r.json())["job_id"]
        await s.settle()
        r = await s.client.post(f"/api/v1/jobs/{jid}/cancel", headers=s.h())
        assert r.status == 200
        await s.settle()
        meta = await s.job_store.get_meta(jid)
        assert meta["state"] == "CANCELLED"
