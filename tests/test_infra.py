"""Infra-kernel tests: KV semantics, bus delivery, job store state machine,
pointer store, DLQ, locks, artifacts, schema registry, configsvc, secrets."""
import asyncio

import pytest

from cordum_tpu.infra.artifacts import ArtifactStore
from cordum_tpu.infra.bus import LoopbackBus, RetryAfter, compute_msg_id
from cordum_tpu.infra.configsvc import ConfigService
from cordum_tpu.infra.dlq import DLQEntry, DLQStore
from cordum_tpu.infra.jobstore import IllegalTransition, JobStore
from cordum_tpu.infra.kv import MemoryKV, key_from_pointer, pointer_for_key
from cordum_tpu.infra.locks import LockStore
from cordum_tpu.infra.memstore import MemoryStore
from cordum_tpu.infra.registry import WorkerRegistry
from cordum_tpu.infra.schemareg import SchemaError, SchemaRegistry
from cordum_tpu.infra.secrets import contains_secret_refs, redact_secret_refs
from cordum_tpu.protocol.types import BusPacket, Heartbeat, JobRequest, JobState


# ---------------------------------------------------------------- KV

async def test_kv_basic(kv):
    await kv.set("a", b"1")
    assert await kv.get("a") == b"1"
    assert await kv.setnx("a", b"2") is False
    assert await kv.setnx("b", b"2") is True
    assert await kv.delete("a", "b") == 2


async def test_kv_ttl(kv):
    await kv.set("a", b"1", ttl_s=0.02)
    assert await kv.get("a") == b"1"
    await asyncio.sleep(0.03)
    assert await kv.get("a") is None


async def test_kv_zset(kv):
    await kv.zadd("z", "a", 3)
    await kv.zadd("z", "b", 1)
    await kv.zadd("z", "c", 2)
    assert await kv.zrange("z") == ["b", "c", "a"]
    assert await kv.zrange("z", desc=True) == ["a", "c", "b"]
    assert await kv.zrangebyscore("z", 1, 2) == ["b", "c"]
    assert await kv.zcard("z") == 3
    await kv.zrem("z", "b")
    assert await kv.zcard("z") == 2


async def test_kv_list_hash(kv):
    await kv.rpush("l", b"1", b"2", b"3")
    assert await kv.lrange("l") == [b"1", b"2", b"3"]
    assert await kv.lrange("l", -2) == [b"2", b"3"]
    await kv.ltrim("l", -2, -1)
    assert await kv.llen("l") == 2
    await kv.hset("h", {"x": b"1"})
    assert await kv.hget("h", "x") == b"1"
    assert await kv.hincrby("h", "n", 5) == 5


async def test_kv_commit_conflict(kv):
    await kv.set("w", b"1")
    ver = await kv.version("w")
    assert await kv.commit({"w": ver}, [("set", "w", b"2")]) is True
    # stale version now
    assert await kv.commit({"w": ver}, [("set", "w", b"3")]) is False
    assert await kv.get("w") == b"2"


# ---------------------------------------------------------------- bus

async def test_bus_queue_group_and_fanout():
    bus = LoopbackBus(sync=True)
    got_q, got_all = [], []

    async def qh(name):
        async def h(subject, pkt):
            got_q.append(name)
        return h

    await bus.subscribe("sys.job.submit", await qh("a"), queue="g")
    await bus.subscribe("sys.job.submit", await qh("b"), queue="g")

    async def fan(subject, pkt):
        got_all.append(subject)

    await bus.subscribe("sys.job.>", fan)
    for i in range(4):
        await bus.publish("sys.job.submit", BusPacket.wrap(JobRequest(job_id=f"j{i}", topic="t")))
    assert len(got_q) == 4  # one queue member per message
    assert set(got_q) == {"a", "b"}  # round-robin hit both
    assert len(got_all) == 4


async def test_bus_retry_after_redelivers():
    bus = LoopbackBus()
    attempts = []

    async def h(subject, pkt):
        attempts.append(1)
        if len(attempts) < 3:
            raise RetryAfter(0.01)

    await bus.subscribe("sys.job.submit", h, queue="g")
    await bus.publish("sys.job.submit", BusPacket.wrap(JobRequest(job_id="j1", topic="t")))
    await bus.drain()
    assert len(attempts) == 3


async def test_bus_msg_id_dedupe():
    bus = LoopbackBus()
    got = []

    async def h(subject, pkt):
        got.append(pkt.job_request.job_id)

    await bus.subscribe("sys.job.submit", h, queue="g")
    req = JobRequest(job_id="same", topic="t")
    await bus.publish("sys.job.submit", BusPacket.wrap(req))
    await bus.publish("sys.job.submit", BusPacket.wrap(req))  # duplicate msg-id
    await bus.drain()
    assert got == ["same"]
    # label override forces distinct ids
    r2 = JobRequest(job_id="same", topic="t", labels={"cordum.bus_msg_id": "other"})
    await bus.publish("sys.job.submit", BusPacket.wrap(r2))
    await bus.drain()
    assert len(got) == 2


def test_msg_id_heartbeats_not_deduped():
    hb = Heartbeat(worker_id="w1")
    a = compute_msg_id("sys.heartbeat", BusPacket.wrap(hb))
    b = compute_msg_id("sys.heartbeat", BusPacket.wrap(hb))
    assert a != b  # time-bucketed


# ---------------------------------------------------------------- job store

async def test_jobstore_lifecycle(kv):
    js = JobStore(kv)
    await js.set_state("j1", JobState.PENDING, fields={"topic": "job.x", "tenant_id": "t"})
    await js.set_state("j1", JobState.SCHEDULED)
    await js.set_state("j1", JobState.DISPATCHED)
    await js.set_state("j1", JobState.RUNNING)
    await js.set_state("j1", JobState.SUCCEEDED, fields={"result_ptr": "kv://res:j1"})
    meta = await js.get_meta("j1")
    assert meta["state"] == "SUCCEEDED"
    assert meta["result_ptr"] == "kv://res:j1"
    assert "finished_at_us" in meta
    assert await js.list_by_state("SUCCEEDED") == ["j1"]
    assert await js.list_by_state("RUNNING") == []
    assert "j1" in await js.list_recent()


async def test_jobstore_illegal(kv):
    js = JobStore(kv)
    await js.set_state("j1", JobState.PENDING)
    with pytest.raises(IllegalTransition):
        await js.set_state("j1", JobState.SUCCEEDED)
    await js.set_state("j1", JobState.RUNNING)
    await js.set_state("j1", JobState.SUCCEEDED)
    with pytest.raises(IllegalTransition):
        await js.set_state("j1", JobState.RUNNING)  # terminal immutable
    # idempotent re-apply returns False, no error
    assert await js.set_state("j1", JobState.SUCCEEDED) is False


async def test_jobstore_events_trace_deadline(kv):
    js = JobStore(kv)
    await js.set_state("j1", JobState.PENDING, event="submit")
    await js.append_event("j1", "custom", detail="x")
    evs = await js.events("j1")
    assert evs[0]["event"] == "submit"
    assert evs[-1]["detail"] == "x"
    await js.add_to_trace("tr1", "j1")
    assert await js.trace("tr1") == {"j1"}
    await js.register_deadline("j1", 1000)
    assert await js.expired_deadlines(2000) == ["j1"]
    await js.clear_deadline("j1")
    assert await js.expired_deadlines(2000) == []


async def test_jobstore_idempotency_and_locks(kv):
    js = JobStore(kv)
    ok, jid = await js.try_set_idempotency_key("t1", "k", "j1")
    assert ok and jid == "j1"
    ok, jid = await js.try_set_idempotency_key("t1", "k", "j2")
    assert not ok and jid == "j1"
    ok, _ = await js.try_set_idempotency_key("t2", "k", "j3")  # scoped
    assert ok
    assert await js.acquire_job_lock("j1", "s1")
    assert not await js.acquire_job_lock("j1", "s2")
    await js.release_job_lock("j1", "s2")  # wrong owner: no-op
    assert not await js.acquire_job_lock("j1", "s2")
    await js.release_job_lock("j1", "s1")
    assert await js.acquire_job_lock("j1", "s2")


async def test_jobstore_request_persistence(kv):
    js = JobStore(kv)
    req = JobRequest(job_id="j1", topic="job.x", tenant_id="t")
    await js.put_request(req)
    back = await js.get_request("j1")
    assert back.topic == "job.x"


async def test_jobstore_tenant_counts(kv):
    js = JobStore(kv)
    await js.tenant_active_add("t", "j1")
    await js.tenant_active_add("t", "j2")
    assert await js.tenant_active_count("t") == 2
    # terminal transition clears membership
    await js.set_state("j1", JobState.PENDING, fields={"tenant_id": "t"})
    await js.set_state("j1", JobState.RUNNING)
    await js.set_state("j1", JobState.FAILED)
    assert await js.tenant_active_count("t") == 1


# ---------------------------------------------------------------- stores

async def test_memstore_pointers(kv):
    ms = MemoryStore(kv)
    ptr = await ms.put_context("j1", {"input": "hi"})
    assert ptr == "kv://ctx:j1"
    assert await ms.get_context(ptr) == {"input": "hi"}
    assert await ms.get_context("j1") == {"input": "hi"}
    rptr = await ms.put_result("j1", {"out": 1})
    assert await ms.get_pointer(rptr) == {"out": 1}
    assert key_from_pointer("redis://ctx:x") == "ctx:x"  # legacy scheme accepted
    assert pointer_for_key("res:j") == "kv://res:j"


async def test_dlq(kv):
    d = DLQStore(kv)
    await d.add(DLQEntry(job_id="j1", topic="t", reason="boom", reason_code="FAILED"))
    await d.add(DLQEntry(job_id="j2", topic="t", reason="denied"))
    assert await d.count() == 2
    entries = await d.list()
    assert entries[0].job_id == "j2"  # newest first
    assert await d.delete("j1")
    assert await d.count() == 1


async def test_locks(kv):
    ls = LockStore(kv)
    assert await ls.acquire("r1", "a", ttl_s=5)
    assert not await ls.acquire("r1", "b")
    assert await ls.acquire("r1", "a")  # re-entrant
    assert await ls.release("r1", "a")
    assert await ls.acquire("r1", "b", mode="shared")
    assert await ls.acquire("r1", "c", mode="shared")
    assert not await ls.acquire("r1", "d", mode="exclusive")
    info = await ls.get("r1")
    assert set(info.owners) == {"b", "c"}


async def test_artifacts(kv):
    a = ArtifactStore(kv)
    meta = await a.put(b"hello", content_type="text/plain", retention="short")
    data, m2 = await a.get(meta.artifact_id)
    assert data == b"hello"
    assert m2.content_type == "text/plain"
    assert a.pointer(meta.artifact_id) == f"kv://art:{meta.artifact_id}"


async def test_schema_registry(kv):
    r = SchemaRegistry(kv)
    await r.put("s1", {"type": "object", "required": ["x"]})
    assert await r.validate_id("s1", {"x": 1}) == []
    errs = await r.validate_id("s1", {})
    assert errs
    with pytest.raises(SchemaError):
        await r.validate_id("missing", {})
    assert "s1" in await r.list()


async def test_configsvc_effective(kv):
    c = ConfigService(kv)
    await c.set("system", "default", {"a": 1, "b": 1})
    await c.set("org", "acme", {"b": 2, "c": 2})
    await c.set("workflow", "wf1", {"c": 3})
    eff = await c.effective(org="acme", workflow="wf1")
    assert eff == {"a": 1, "b": 2, "c": 3}
    snap1 = await c.effective_snapshot(org="acme")
    await c.patch("org", "acme", {"b": None, "d": 4})
    doc = await c.get("org", "acme")
    assert doc.revision == 2 and "b" not in doc.data and doc.data["d"] == 4
    snap2 = await c.effective_snapshot(org="acme")
    assert snap1["hash"] != snap2["hash"]


def test_secrets():
    v = {"key": "secret://vault/x", "nested": [{"a": "plain"}]}
    assert contains_secret_refs(v)
    red = redact_secret_refs(v)
    assert red["key"] == "[redacted:secret-ref]"
    assert red["nested"][0]["a"] == "plain"
    assert not contains_secret_refs({"a": "b"})


def test_registry_ttl():
    reg = WorkerRegistry(ttl_s=0.0)  # everything instantly stale
    reg.update(Heartbeat(worker_id="w1"))
    assert reg.snapshot() == {}
    reg2 = WorkerRegistry()
    reg2.update(Heartbeat(worker_id="w1", active_jobs=2))
    assert reg2.get("w1").active_jobs == 2
    assert reg2.expire() == []
