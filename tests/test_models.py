"""Model + parallelism tests on the virtual 8-device CPU mesh."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cordum_tpu.models import embedder as emb
from cordum_tpu.models import llama
from cordum_tpu.ops.ring_attention import reference_attention, ring_attention
from cordum_tpu.parallel import mesh as meshlib


def test_eight_devices_available():
    assert jax.device_count() == 8


def test_mesh_spec_resolution():
    assert meshlib.MeshSpec(dp=-1, tp=2).resolve(8) == {"dp": 4, "tp": 2, "sp": 1, "ep": 1, "pp": 1}
    with pytest.raises(ValueError):
        meshlib.MeshSpec(dp=3, tp=2).resolve(8)
    m = meshlib.build_mesh(meshlib.MeshSpec(dp=-1, tp=2, sp=2))
    assert m.shape["dp"] == 2 and m.shape["tp"] == 2 and m.shape["sp"] == 2


def test_simple_mesh_and_topology():
    m = meshlib.simple_mesh(4)
    assert m.shape == {"dp": 2, "tp": 4}
    assert meshlib.slice_topology() == "8"  # CPU devices: flat count


# ---------------------------------------------------------------- llama

def test_llama_forward_shapes_and_determinism():
    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, cfg.vocab_size)
    logits = llama.forward(params, tokens, cfg)
    assert logits.shape == (2, 16, cfg.vocab_size)
    logits2 = llama.forward(params, tokens, cfg)
    np.testing.assert_allclose(np.asarray(logits, np.float32), np.asarray(logits2, np.float32))


def test_llama_causality():
    """Changing a future token must not change past logits."""
    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    t1 = jnp.zeros((1, 8), jnp.int32).at[0, 7].set(5)
    t2 = jnp.zeros((1, 8), jnp.int32).at[0, 7].set(9)
    l1 = llama.forward(params, t1, cfg)
    l2 = llama.forward(params, t2, cfg)
    np.testing.assert_allclose(
        np.asarray(l1[:, :7], np.float32), np.asarray(l2[:, :7], np.float32), atol=1e-5
    )
    assert not np.allclose(np.asarray(l1[:, 7], np.float32), np.asarray(l2[:, 7], np.float32))


def test_llama_sharded_forward_matches_single_device():
    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, cfg.vocab_size)
    ref = llama.forward(params, tokens, cfg)

    mesh = meshlib.build_mesh(meshlib.MeshSpec(dp=2, tp=2, sp=2))
    sparams = llama.shard_params(params, cfg, mesh)
    fwd = jax.jit(lambda p, t: llama.forward(p, t, cfg, mesh=mesh))
    out = fwd(sparams, tokens)
    # bf16 accumulation order differs across shardings; require close logits
    # plus near-total argmax agreement
    o = np.asarray(out, np.float32)
    r = np.asarray(ref, np.float32)
    assert np.mean(np.abs(o - r) < 0.1) > 0.995
    agree = np.mean(o.argmax(-1) == r.argmax(-1))
    assert agree > 0.98, f"argmax agreement {agree}"


def test_llama_ring_attention_matches_gather_flavor():
    """use_ring_attention must produce the same logits as the KV-gather CP."""
    import dataclasses

    cfg = llama.LlamaConfig(vocab_size=128, d_model=64, n_layers=2, n_heads=4,
                            n_kv_heads=2, d_ff=128, dtype=jnp.float32)
    ring_cfg = dataclasses.replace(cfg, use_ring_attention=True)
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, cfg.vocab_size)
    mesh = meshlib.build_mesh(meshlib.MeshSpec(dp=2, tp=1, sp=4))
    sparams = llama.shard_params(params, cfg, mesh)
    gather = jax.jit(lambda p, t: llama.forward(p, t, cfg, mesh=mesh))(sparams, tokens)
    ring = jax.jit(lambda p, t: llama.forward(p, t, ring_cfg, mesh=mesh))(sparams, tokens)
    np.testing.assert_allclose(
        np.asarray(ring, np.float32), np.asarray(gather, np.float32), atol=1e-3, rtol=1e-3
    )


def test_llama_train_step_runs_sharded():
    cfg = llama.LlamaConfig.tiny()
    mesh = meshlib.build_mesh(meshlib.MeshSpec(dp=2, tp=2, sp=2))
    init, step = llama.make_train_step(cfg, mesh)
    params, opt_state = init(jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, cfg.vocab_size)
    params, opt_state, loss1 = step(params, opt_state, tokens)
    params, opt_state, loss2 = step(params, opt_state, tokens)
    assert float(loss2) < float(loss1)  # it learns the batch
    # params keep their TP sharding through the step
    wq = params["layers"][0]["wq"]
    assert len(wq.sharding.device_set) == 8


# ---------------------------------------------------------------- embedder

def test_embedder_tokenizer_deterministic():
    cfg = emb.EmbedderConfig()
    a = emb.tokenize("Hello, TPU world!", cfg)
    b = emb.tokenize("Hello, TPU world!", cfg)
    assert a == b and a[0] == 1 and len(a) > 1
    ids, mask = emb.batch_tokenize(["short", "a much longer sentence here"], cfg)
    assert ids.shape == (2, cfg.max_len)
    assert mask[0].sum() < mask[1].sum()


def test_embedder_similarity_sanity():
    e = emb.Embedder(emb.EmbedderConfig(n_layers=2, d_model=128, max_len=32), seed=0)
    vecs = e.embed([
        "the scheduler dispatches jobs to workers",
        "the scheduler dispatches jobs to workers",
        "quantum chromodynamics lattice simulation",
    ])
    assert vecs.shape == (3, 128)
    np.testing.assert_allclose(np.linalg.norm(vecs, axis=1), 1.0, atol=1e-3)
    assert float(vecs[0] @ vecs[1]) == pytest.approx(1.0, abs=1e-3)  # identical text
    assert float(vecs[0] @ vecs[2]) < 0.999  # different text separates


def test_embedder_sharded_matches_unsharded():
    cfg = emb.EmbedderConfig(n_layers=2, d_model=128, max_len=32)
    e1 = emb.Embedder(cfg, seed=3)
    mesh = meshlib.simple_mesh(1)  # dp=8
    e2 = emb.Embedder(cfg, seed=3, mesh=mesh)
    texts = [f"document number {i} about scheduling" for i in range(5)]  # non-multiple of 8
    v1 = e1.embed(texts)
    v2 = e2.embed(texts)
    np.testing.assert_allclose(v1, v2, atol=2e-2)


# ---------------------------------------------------------------- ring attention

@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_matches_reference(causal):
    mesh = meshlib.build_mesh(meshlib.MeshSpec(dp=2, tp=1, sp=4))
    b, t, h, hkv, d = 2, 32, 4, 2, 16
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(ks[0], (b, t, h, d), jnp.float32)
    k = jax.random.normal(ks[1], (b, t, hkv, d), jnp.float32)
    v = jax.random.normal(ks[2], (b, t, hkv, d), jnp.float32)
    ref = reference_attention(q, k, v, causal=causal)
    out = ring_attention(q, k, v, mesh, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-4, rtol=1e-4)


def test_ring_attention_jits_inside_training_style_fn():
    mesh = meshlib.build_mesh(meshlib.MeshSpec(dp=1, tp=1, sp=8))
    b, t, h, d = 1, 64, 2, 8
    q = jax.random.normal(jax.random.PRNGKey(0), (b, t, h, d))
    fn = jax.jit(lambda q: ring_attention(q, q, q, mesh).sum())
    v1 = float(fn(q))
    ref = float(reference_attention(q, q, q).sum())
    assert abs(v1 - ref) < 1e-2
