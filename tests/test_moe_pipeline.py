"""MoE (ep) and pipeline (pp) model families on the 8-device CPU mesh."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cordum_tpu.models import llama, moe, pipeline
from cordum_tpu.parallel import mesh as meshlib


def test_moe_forward_shapes_and_aux():
    cfg = moe.MoEConfig.tiny()
    params = moe.init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, cfg.base.vocab_size)
    logits, aux = moe.forward(params, tokens, cfg)
    assert logits.shape == (2, 16, cfg.base.vocab_size)
    assert float(aux["moe_aux_loss"]) > 0.0


def test_moe_sharded_train_step_ep_axis():
    cfg = moe.MoEConfig.tiny()
    mesh = meshlib.build_mesh(meshlib.MeshSpec(dp=2, tp=2, ep=2))
    init, step = moe.make_train_step(cfg, mesh)
    params, opt_state = init(jax.random.PRNGKey(0))
    # expert weights actually sharded over ep
    wg = params["layers"][0]["moe"]["w_gate"]
    assert "ep" in str(wg.sharding.spec)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, cfg.base.vocab_size)
    params, opt_state, l1 = step(params, opt_state, tokens)
    params, opt_state, l2 = step(params, opt_state, tokens)
    assert float(l2) < float(l1)


def test_moe_capacity_drops_dont_crash():
    cfg = moe.MoEConfig(base=llama.LlamaConfig.tiny(), n_experts=2, top_k=1, capacity_factor=0.25)
    params = moe.init_params(jax.random.PRNGKey(0), cfg)
    tokens = jnp.zeros((2, 32), jnp.int32)  # all tokens route identically → overflow
    logits, aux = moe.forward(params, tokens, cfg)
    assert np.isfinite(np.asarray(logits, np.float32)).all()


def test_pipeline_loss_matches_sequential_reference():
    """The pp=4 pipelined loss must equal the same model run sequentially."""
    base = llama.LlamaConfig(vocab_size=128, d_model=32, n_layers=4, n_heads=2,
                             n_kv_heads=2, d_ff=64, dtype=jnp.float32)
    cfg = pipeline.PipelineConfig(base=base, n_stages=4, n_microbatches=2)
    params = pipeline.init_params(jax.random.PRNGKey(0), cfg)
    mesh = meshlib.build_mesh(meshlib.MeshSpec(dp=2, pp=4))
    loss_fn = pipeline.make_loss_fn(cfg, mesh)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 12), 0, base.vocab_size)
    tokens_mb = pipeline.microbatch(tokens, cfg.n_microbatches)
    pipe_loss = float(jax.jit(loss_fn)(params, tokens_mb))

    # sequential reference: flatten stages into one layer list
    def seq_loss(params, tokens):
        stages = params["stages"]
        x = params["embed"][tokens].astype(base.dtype)
        positions = jnp.broadcast_to(jnp.arange(tokens.shape[1]), tokens.shape)
        for s in range(cfg.n_stages):
            stage_params = jax.tree.map(lambda p: p[s], stages)
            x = pipeline._stage_apply(stage_params, x, positions, base)
        h = llama.rms_norm(x, params["final_norm"], base.norm_eps)
        logits = (h @ params["lm_head"]).astype(jnp.float32)
        logp = jax.nn.log_softmax(logits[:, :-1], axis=-1)
        nll = -jnp.take_along_axis(logp, tokens[:, 1:][..., None], axis=-1)[..., 0]
        return jnp.mean(nll)

    ref_loss = float(seq_loss(params, tokens))
    assert pipe_loss == pytest.approx(ref_loss, rel=1e-4), (pipe_loss, ref_loss)


def test_pipeline_train_step_learns():
    base = llama.LlamaConfig(vocab_size=64, d_model=32, n_layers=2, n_heads=2,
                             n_kv_heads=2, d_ff=64, dtype=jnp.float32)
    cfg = pipeline.PipelineConfig(base=base, n_stages=2, n_microbatches=2)
    mesh = meshlib.build_mesh(meshlib.MeshSpec(dp=4, pp=2))
    init, step = pipeline.make_train_step(cfg, mesh)
    params, opt_state = init(jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 12), 0, base.vocab_size)
    mbs = pipeline.microbatch(tokens, cfg.n_microbatches)
    params, opt_state, l1 = step(params, opt_state, mbs)
    params, opt_state, l2 = step(params, opt_state, mbs)
    params, opt_state, l3 = step(params, opt_state, mbs)
    assert float(l3) < float(l1)
    # stage params stay pp-sharded through the step
    assert "pp" in str(params["stages"]["wq"].sharding.spec)
