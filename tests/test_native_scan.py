"""Native C worker-selection scan: correctness vs the Python scan, and the
selection-throughput microbenchmark shape."""
import random

import pytest

from cordum_tpu.controlplane.scheduler.strategy import (
    LeastLoadedStrategy,
    is_overloaded,
    load_score,
    worker_satisfies,
)
from cordum_tpu.infra.config import parse_pool_config
from cordum_tpu.infra.registry import WorkerRegistry
from cordum_tpu.native import load_strategy_scan
from cordum_tpu.protocol.types import Heartbeat, JobMetadata, JobRequest

pytestmark = pytest.mark.skipif(
    load_strategy_scan() is None, reason="no C compiler available"
)


def random_registry(n, seed=0):
    rng = random.Random(seed)
    reg = WorkerRegistry()
    for i in range(n):
        reg.update(Heartbeat(
            worker_id=f"w{i:05d}",
            pool=rng.choice(["tpu", "cpu"]),
            capabilities=rng.choice([["tpu"], ["tpu", "echo"], ["echo"]]),
            chip_count=rng.choice([1, 4, 8]),
            slice_topology=rng.choice(["", "2x2x1", "2x2x2"]),
            active_jobs=rng.randint(0, 12),
            max_parallel_jobs=10,
            cpu_load=rng.uniform(0, 100),
            tpu_duty_cycle=rng.uniform(0, 100),
            devices_healthy=rng.random() > 0.05,
            hbm_total_gb=rng.choice([0.0, 16.0]),
            hbm_used_gb=rng.uniform(0, 16.0),
        ))
    return reg


POOL_DOC = {"topics": {"job.tpu.work": "tpu"}, "pools": {"tpu": {"requires": ["tpu"]}}}


@pytest.mark.parametrize("requires", [[], ["chips:8"], ["topology:2x2x1"], ["chips:4", "tpu"]])
def test_native_matches_python(requires):
    reg = random_registry(300, seed=42)
    native = LeastLoadedStrategy(reg, parse_pool_config(POOL_DOC), native=True)
    python = LeastLoadedStrategy(reg, parse_pool_config(POOL_DOC), native=False)
    assert native._packed is not None, "native scan should be available"
    req = JobRequest(job_id="j", topic="job.tpu.work",
                     metadata=JobMetadata(requires=requires))
    assert native.pick_subject(req) == python.pick_subject(req)


def test_native_matches_python_across_registry_mutations():
    reg = random_registry(100, seed=7)
    native = LeastLoadedStrategy(reg, parse_pool_config(POOL_DOC), native=True)
    python = LeastLoadedStrategy(reg, parse_pool_config(POOL_DOC), native=False)
    req = JobRequest(job_id="j", topic="job.tpu.work")
    assert native.pick_subject(req) == python.pick_subject(req)
    # heartbeat mutation invalidates the packed cache
    reg.update(Heartbeat(worker_id="w00001", pool="tpu", capabilities=["tpu"],
                         active_jobs=0, max_parallel_jobs=100))
    assert native.pick_subject(req) == python.pick_subject(req)
    reg.remove("w00001")
    assert native.pick_subject(req) == python.pick_subject(req)


def test_native_skips_hbm_full_worker():
    """The HBM pressure gate (is_overloaded's memory leg) must hold on the
    native path too: the C kernel computes the load legs itself but only
    sees HBM through the packed eligibility byte."""
    reg = WorkerRegistry()
    reg.update(Heartbeat(worker_id="w_full", pool="tpu", capabilities=["tpu"],
                         max_parallel_jobs=10,
                         hbm_used_gb=15.8, hbm_total_gb=16.0))
    reg.update(Heartbeat(worker_id="w_ok", pool="tpu", capabilities=["tpu"],
                         max_parallel_jobs=10, active_jobs=5,
                         hbm_used_gb=1.0, hbm_total_gb=16.0))
    assert is_overloaded(reg.get("w_full"))
    strat = LeastLoadedStrategy(reg, parse_pool_config(POOL_DOC), native=True)
    req = JobRequest(job_id="j", topic="job.tpu.work")
    # w_full is idle but memory-saturated; the busier w_ok must win
    assert strat.pick_subject(req) == "worker.w_ok.jobs"


def test_native_no_eligible_falls_to_topic():
    reg = random_registry(50, seed=3)
    strat = LeastLoadedStrategy(reg, parse_pool_config(POOL_DOC), native=True)
    req = JobRequest(job_id="j", topic="job.tpu.work",
                     metadata=JobMetadata(requires=["chips:999"]))
    assert strat.pick_subject(req) == "job.tpu.work"


def test_native_hints_use_python_path():
    reg = random_registry(50, seed=4)
    strat = LeastLoadedStrategy(reg, parse_pool_config(POOL_DOC), native=True)
    req = JobRequest(job_id="j", topic="job.tpu.work",
                     labels={"placement.zone": "nowhere"})
    assert strat.pick_subject(req) == "job.tpu.work"  # no zone labels → fan-in


def test_selection_throughput_native_vs_python():
    import time

    reg = random_registry(1000, seed=9)
    native = LeastLoadedStrategy(reg, parse_pool_config(POOL_DOC), native=True)
    python = LeastLoadedStrategy(reg, parse_pool_config(POOL_DOC), native=False)
    req = JobRequest(job_id="j", topic="job.tpu.work")
    native.pick_subject(req)  # warm the pack

    n = 2000
    t0 = time.perf_counter()
    for _ in range(n):
        native.pick_subject(req)
    t_native = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(n // 10):
        python.pick_subject(req)
    t_python = (time.perf_counter() - t0) * 10
    native_rate = n / t_native
    # reference publishes 18,234 selections/s at 1000 workers
    assert native_rate > 20000, f"native scan only {native_rate:.0f}/s"
    assert t_native < t_python, "native scan should beat the python scan"
