"""Flight recorder: end-to-end span propagation through the in-process
stack, assembler critical-path math, collector retention caps, stage
histograms, and the DLQ bulk operations that ride this PR."""
import asyncio
import threading

from aiohttp.test_utils import TestClient, TestServer

from cordum_tpu.controlplane.gateway.app import Gateway
from cordum_tpu.controlplane.gateway.auth import BasicAuthProvider
from cordum_tpu.controlplane.safetykernel.kernel import SafetyKernel
from cordum_tpu.controlplane.scheduler.engine import Engine as Scheduler
from cordum_tpu.controlplane.scheduler.safety_client import SafetyClient
from cordum_tpu.controlplane.scheduler.strategy import LeastLoadedStrategy
from cordum_tpu.infra.bus import LoopbackBus
from cordum_tpu.infra.config import parse_pool_config
from cordum_tpu.infra.dlq import DLQEntry, DLQStore
from cordum_tpu.infra.jobstore import JobStore
from cordum_tpu.infra.kv import MemoryKV
from cordum_tpu.infra.memstore import MemoryStore
from cordum_tpu.infra.metrics import Histogram, Metrics
from cordum_tpu.infra.registry import WorkerRegistry
from cordum_tpu.infra.schemareg import SchemaRegistry
from cordum_tpu.obs import SpanCollector, Tracer, assemble, render_waterfall
from cordum_tpu.obs.tracer import current_trace_context
from cordum_tpu.protocol import subjects as subj
from cordum_tpu.protocol.types import BusPacket, Heartbeat, JobRequest, Span
from cordum_tpu.utils.ids import now_us
from cordum_tpu.worker.runtime import JobContext, Worker
from cordum_tpu.workflow.engine import Engine as WorkflowEngine
from cordum_tpu.workflow.store import WorkflowStore

POLICY = {
    "default_tenant": "default",
    "tenants": {"default": {"allow_topics": ["job.*", "job.>"]}},
    "rules": [],
}


class ObsStack:
    """Gateway + scheduler + embedded traced kernel + worker + collector on
    one loopback bus, behind a live HTTP server."""

    def __init__(self):
        self.kv = MemoryKV()
        self.bus = LoopbackBus()
        self.job_store = JobStore(self.kv)
        self.mem = MemoryStore(self.kv)
        self.kernel = SafetyKernel(
            policy_doc=POLICY, tracer=Tracer("safety-kernel", self.bus)
        )
        self.registry = WorkerRegistry()
        pc = parse_pool_config({"topics": {"job.work": "p"}, "pools": {"p": {}}})
        self.scheduler = Scheduler(
            bus=self.bus, job_store=self.job_store,
            safety=SafetyClient(self.kernel.check),
            strategy=LeastLoadedStrategy(self.registry, pc), registry=self.registry,
        )
        wf_store = WorkflowStore(self.kv)
        self.gw = Gateway(
            kv=self.kv, bus=self.bus, job_store=self.job_store, mem=self.mem,
            kernel=self.kernel, wf_store=wf_store,
            wf_engine=WorkflowEngine(store=wf_store, bus=self.bus, mem=self.mem),
            schemas=SchemaRegistry(self.kv), registry=self.registry,
            auth=BasicAuthProvider(["user-key"], admin_keys=["admin-key"]),
        )
        self.worker = Worker(bus=self.bus, store=self.mem, worker_id="w1", pool="p",
                             topics=["job.work"], heartbeat_interval_s=999)
        self.client = None

    async def __aenter__(self):
        async def handler(ctx: JobContext):
            p = ctx.payload if isinstance(ctx.payload, dict) else {}
            if p.get("fail"):
                raise RuntimeError("worker failure requested")
            with ctx.device_timer("device", op="test"):
                pass
            return {"done": True}

        self.worker.register("job.work", handler)
        self.registry.update(Heartbeat(worker_id="w1", pool="p", max_parallel_jobs=64))
        await self.kernel.reload()
        await self.scheduler.start()
        await self.worker.start()
        await self.gw.span_collector.start()
        self.gw._subs.append(await self.bus.subscribe(subj.DLQ, self.gw._tap_dlq))
        self.client = TestClient(TestServer(self.gw.app))
        await self.client.start_server()
        return self

    async def __aexit__(self, *exc):
        await self.client.close()
        await self.worker.stop()
        await self.scheduler.stop()
        await self.gw.span_collector.stop()
        for s in self.gw._subs:
            s.unsubscribe()
        await self.bus.close()

    async def settle(self, rounds=30):
        for _ in range(rounds):
            await self.bus.drain()
            await asyncio.sleep(0.01)

    def h(self, admin=False):
        return {"X-Api-Key": "admin-key" if admin else "user-key"}


# ---------------------------------------------------------------------------
# end-to-end propagation
# ---------------------------------------------------------------------------


async def test_span_propagation_end_to_end():
    async with ObsStack() as s:
        r = await s.client.post("/api/v1/jobs", headers=s.h(),
                                json={"topic": "job.work", "payload": {"x": 1}})
        assert r.status == 202
        doc = await r.json()
        trace_id = doc["trace_id"]
        await s.settle()
        assert await s.job_store.get_state(doc["job_id"]) == "SUCCEEDED"

        r = await s.client.get(f"/api/v1/traces/{trace_id}", headers=s.h())
        trace = await r.json()
        assert trace["span_count"] >= 5, trace
        assert {"gateway", "scheduler", "safety-kernel", "worker"} <= set(trace["services"])
        names = {sp["name"] for sp in trace["spans"]}
        assert {"submit", "schedule", "policy-check", "evaluate", "strategy",
                "dispatch", "execute", "device", "result"} <= names

        # tree consistency: every parent resolves, children start after
        # their parent, every span's clock is monotonic
        by_id = {sp["span_id"]: sp for sp in trace["spans"]}
        for sp in trace["spans"]:
            assert sp["start_us"] <= sp["end_us"]
            if sp["parent_span_id"]:
                parent = by_id.get(sp["parent_span_id"])
                assert parent is not None, f"orphan span {sp['name']}"
                assert sp["start_us"] >= parent["start_us"]
        # exactly one root: the gateway submit span
        roots = [sp for sp in trace["spans"] if not sp["parent_span_id"]]
        assert [sp["name"] for sp in roots] == ["submit"]
        assert trace["critical_path"], trace
        # stage table covers the canonical dispatch path
        assert trace["stages"]["execute"]["count"] == 1
        # the jobs grouping (legacy shape) still rides along
        assert trace["jobs"][0]["state"] == "SUCCEEDED"

        # per-stage histograms reached the gateway's /metrics
        r = await s.client.get("/metrics")
        text = await r.text()
        assert 'cordum_stage_seconds_count{service="worker",stage="execute"} 1' in text
        assert 'cordum_stage_seconds_count{service="gateway",stage="submit"} 1' in text

        # the CLI renderer consumes the same JSON
        out = render_waterfall(trace)
        assert f"trace {trace_id}" in out and "execute" in out


async def test_failed_job_span_marks_error():
    async with ObsStack() as s:
        r = await s.client.post("/api/v1/jobs", headers=s.h(),
                                json={"topic": "job.work", "payload": {"fail": True}})
        doc = await r.json()
        await s.settle()
        spans = await s.gw.span_collector.spans(doc["trace_id"])
        execute = [sp for sp in spans if sp.name == "execute"]
        assert execute and execute[0].status == "ERROR"
        assert execute[0].attrs.get("error_code") == "RuntimeError"


async def test_workflow_step_dispatch_traced(kv, bus):
    mem = MemoryStore(kv)
    store = WorkflowStore(kv)
    eng = WorkflowEngine(store=store, bus=bus, mem=mem)
    collector = SpanCollector(kv, bus)
    await collector.start()
    from cordum_tpu.workflow.models import Workflow

    wf = Workflow.from_dict({"id": "wf1", "name": "wf1",
                             "steps": {"a": {"topic": "job.work", "input": {"k": 1}}}})
    await store.put_workflow(wf)
    run = await eng.start_run("wf1", {"x": 1})
    await bus.drain()
    # the dispatched packet opened its own trace rooted at step-dispatch
    submit = [(subject, p) for subject, p in bus.published if subject == subj.SUBMIT]
    assert submit and submit[0][1].span_id
    spans = await collector.spans(submit[0][1].trace_id)
    assert [sp.name for sp in spans] == ["step-dispatch"]
    assert spans[0].attrs["run_id"] == run.run_id
    await collector.stop()


# ---------------------------------------------------------------------------
# assembler
# ---------------------------------------------------------------------------


def _mk(span_id, parent, name, start, end, service="svc"):
    return Span(span_id=span_id, parent_span_id=parent, trace_id="t",
                name=name, service=service, start_us=start, end_us=end)


def test_assembler_critical_path():
    spans = [
        _mk("a", "", "submit", 0, 100),
        _mk("b", "a", "schedule", 10, 40),
        _mk("c", "a", "dispatch", 40, 95),  # latest-ending child of a
        _mk("d", "c", "execute", 50, 90),
        _mk("e", "c", "policy-check", 45, 60),
    ]
    doc = assemble("t", spans)
    assert doc["critical_path"] == ["a", "c", "d"]
    assert doc["critical_path_us"] == 100  # root start → latest end on path
    assert doc["total_us"] == 100
    assert doc["span_count"] == 5
    depths = {sp["span_id"]: sp["depth"] for sp in doc["spans"]}
    assert depths == {"a": 0, "b": 1, "c": 1, "d": 2, "e": 2}
    assert doc["stages"]["execute"] == {"total_us": 40, "count": 1}
    # rows come back in start order
    assert [sp["span_id"] for sp in doc["spans"]] == ["a", "b", "c", "e", "d"]


def test_assembler_orphans_become_roots():
    spans = [
        _mk("x", "gone", "execute", 10, 30),
        _mk("y", "x", "device", 15, 25),
    ]
    doc = assemble("t", spans)
    assert doc["critical_path"] == ["x", "y"]
    assert doc["spans"][0]["depth"] == 0
    assert "no spans" in render_waterfall(assemble("t", []))


def test_assembler_stage_aggregation_sums_retries():
    spans = [
        _mk("a", "", "schedule", 0, 10),
        _mk("b", "", "schedule", 20, 50),
    ]
    doc = assemble("t", spans)
    assert doc["stages"]["schedule"] == {"total_us": 40, "count": 2}


# ---------------------------------------------------------------------------
# collector retention
# ---------------------------------------------------------------------------


async def test_collector_span_ring_buffer_cap(kv, bus):
    c = SpanCollector(kv, bus, max_spans_per_trace=5)
    for i in range(12):
        await c.add(_mk(f"s{i:02d}", "", "execute", i, i + 1))
    spans = await c.spans("t")
    assert len(spans) == 5
    assert [sp.span_id for sp in spans] == ["s07", "s08", "s09", "s10", "s11"]


async def test_collector_trace_eviction_cap(kv, bus):
    c = SpanCollector(kv, bus, max_traces=3)
    for i in range(6):
        sp = _mk(f"s{i}", "", "execute", i, i + 1)
        sp.trace_id = f"trace-{i}"
        await c.add(sp)
    alive = [t for t in (f"trace-{i}" for i in range(6)) if await c.spans(t)]
    assert alive == ["trace-3", "trace-4", "trace-5"]


async def test_collector_purge_older_than(kv, bus):
    c = SpanCollector(kv, bus)
    await c.add(_mk("a", "", "execute", 0, 1))
    assert await c.purge_older_than(now_us() + 1) == 1
    assert await c.spans("t") == []


async def test_collector_consumes_bus_spans(kv, bus):
    metrics = Metrics()
    c = SpanCollector(kv, bus, metrics=metrics)
    await c.start()
    t = Tracer("scheduler", bus)
    async with t.span("schedule", trace_id="tr-1"):
        pass
    await bus.drain()
    spans = await c.spans("tr-1")
    assert [sp.name for sp in spans] == ["schedule"]
    assert metrics.stage_seconds.quantile(0.5, stage="schedule", service="scheduler") is not None
    await c.stop()


# ---------------------------------------------------------------------------
# tracer context propagation
# ---------------------------------------------------------------------------


async def _sink(subject, pkt):
    return None


async def test_tracer_nested_spans_inherit_parent(bus):
    # a listener must exist: with no TRACE_SPAN subscriber the tracer
    # skips span publishing entirely (the 1×1 fast path)
    await bus.subscribe(subj.TRACE_SPAN, _sink)
    t = Tracer("svc", bus)
    async with t.span("outer", trace_id="tr") as outer:
        assert current_trace_context() == ("tr", outer.span_id)
        async with t.span("inner") as inner:
            assert inner.trace_id == "tr"
            assert inner.parent_span_id == outer.span_id
    assert current_trace_context() == ("", "")
    published = [p for s, p in bus.published if s == subj.TRACE_SPAN]
    assert [p.payload.name for p in published] == ["inner", "outer"]


async def test_tracer_untraced_spans_not_published(bus):
    await bus.subscribe(subj.TRACE_SPAN, _sink)
    t = Tracer("svc", bus)
    async with t.span("orphan") as sp:
        assert sp.trace_id == ""
    assert not [p for s, p in bus.published if s == subj.TRACE_SPAN]


async def test_tracer_error_marks_span(bus):
    await bus.subscribe(subj.TRACE_SPAN, _sink)
    t = Tracer("svc", bus)
    try:
        async with t.span("boom", trace_id="tr"):
            raise ValueError("x")
    except ValueError:
        pass
    (pkt,) = [p for s, p in bus.published if s == subj.TRACE_SPAN]
    assert pkt.payload.status == "ERROR"
    assert pkt.payload.attrs["error"] == "ValueError"


def test_span_wire_roundtrip():
    sp = _mk("a", "b", "execute", 1, 2)
    sp.attrs = {"k": "v"}
    pkt = BusPacket.wrap(sp, trace_id="t", sender_id="w", span_id="a", parent_span_id="b")
    decoded = BusPacket.from_wire(pkt.to_wire())
    assert decoded.span == sp
    assert decoded.span_id == "a" and decoded.parent_span_id == "b"
    # packets without span context keep the lean wire shape
    lean = BusPacket.wrap(JobRequest(job_id="j", topic="job.x"))
    assert "span_id" not in lean.to_dict()


# ---------------------------------------------------------------------------
# metrics: locked reads (satellite fix)
# ---------------------------------------------------------------------------


def test_histogram_render_during_concurrent_observe():
    h = Histogram("h_test", "x")
    stop = threading.Event()
    errors = []

    def writer():
        i = 0
        while not stop.is_set():
            h.observe(0.001 * (i % 50), stage=f"s{i % 3}")
            i += 1

    threads = [threading.Thread(target=writer) for _ in range(4)]
    for th in threads:
        th.start()
    try:
        for _ in range(200):
            for line in h.render():
                assert "h_test" in line
            h.quantile(0.5, stage="s0")
    except Exception as e:  # noqa: BLE001 - the assertion IS the test
        errors.append(e)
    finally:
        stop.set()
        for th in threads:
            th.join()
    assert not errors


# ---------------------------------------------------------------------------
# DLQ bulk operations (satellite)
# ---------------------------------------------------------------------------


async def test_dlq_purge_older_than(kv):
    dlq = DLQStore(kv)
    t0 = now_us()
    await dlq.add(DLQEntry(job_id="old", created_at_us=t0 - 10_000_000))
    await dlq.add(DLQEntry(job_id="new", created_at_us=t0))
    assert await dlq.purge_older_than(t0 - 5_000_000) == 1
    assert await dlq.get("old") is None
    assert await dlq.get("new") is not None


async def test_dlq_retry_all_redrives_and_keeps_failures(kv):
    dlq = DLQStore(kv)
    await dlq.add(DLQEntry(job_id="a", created_at_us=1))
    await dlq.add(DLQEntry(job_id="b", created_at_us=2))
    seen = []

    async def retry_fn(job_id):
        seen.append(job_id)
        return f"new-{job_id}" if job_id == "a" else None

    results = await dlq.retry_all(retry_fn)
    assert seen == ["a", "b"]  # oldest first
    assert dict(results) == {"a": "new-a", "b": None}
    assert await dlq.get("a") is None  # re-driven entry removed
    assert await dlq.get("b") is not None  # failed re-drive stays


async def test_dlq_bulk_routes():
    async with ObsStack() as s:
        # dead-letter a job by making the worker fail it
        r = await s.client.post("/api/v1/jobs", headers=s.h(),
                                json={"topic": "job.work", "payload": {"fail": True}})
        jid = (await r.json())["job_id"]
        await s.settle()
        assert await s.gw.dlq.count() == 1

        # non-admin denied
        r = await s.client.post("/api/v1/dlq/retry-all", headers=s.h())
        assert r.status == 403
        r = await s.client.post("/api/v1/dlq/purge", headers=s.h(admin=True), json={})
        assert r.status == 400  # cutoff required

        r = await s.client.post("/api/v1/dlq/retry-all", headers=s.h(admin=True))
        assert r.status == 202
        body = await r.json()
        assert body["count"] == 1
        assert body["retried"][0]["job_id"] == jid
        assert await s.gw.dlq.get(jid) is None
        await s.settle()  # retried job fails again → dead-lettered again
        assert await s.gw.dlq.count() == 1

        r = await s.client.post("/api/v1/dlq/purge", headers=s.h(admin=True),
                                json={"older_than_us": now_us() + 1_000_000})
        assert (await r.json())["purged"] == 1
        assert await s.gw.dlq.count() == 0
