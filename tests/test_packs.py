"""Pack system: manifest loading, install/verify/rollback/uninstall, the
example packs, and the HTTP install path (demo-guardrails acceptance)."""
import pytest

from cordum_tpu.controlplane.safetykernel.kernel import SafetyKernel
from cordum_tpu.infra.configsvc import ConfigService
from cordum_tpu.infra.kv import MemoryKV
from cordum_tpu.infra.schemareg import SchemaRegistry
from cordum_tpu.packs import (
    PackError,
    PackInstaller,
    load_pack_dir,
    manifest_from_doc,
)
from cordum_tpu.protocol.types import JobMetadata, PolicyCheckRequest
from cordum_tpu.workflow.store import WorkflowStore

REPO = "/root/repo"


def make_installer(kv):
    cs = ConfigService(kv)
    kernel = SafetyKernel(policy_doc={"tenants": {"default": {"allow_topics": ["job.*", "job.>"]}}},
                          configsvc=cs)
    return PackInstaller(
        configsvc=cs, schemas=SchemaRegistry(kv), wf_store=WorkflowStore(kv), kernel=kernel
    ), cs, kernel


def test_load_example_packs():
    hello = load_pack_dir(f"{REPO}/examples/hello-pack")
    assert hello.id == "hello-pack" and len(hello.workflows) == 1
    guard = load_pack_dir(f"{REPO}/examples/demo-guardrails")
    assert guard.id == "demo-guardrails"
    assert len(guard.policy_overlays) == 1
    assert len(guard.simulations) == 3


async def test_install_demo_guardrails(kv):
    installer, cs, kernel = make_installer(kv)
    await kernel.reload()
    m = load_pack_dir(f"{REPO}/examples/demo-guardrails")
    record = await installer.install(m)
    assert "guarded-inference" in record["workflows"]
    # policy fragment live: destructive denied, tpu constrained
    resp = await kernel.evaluate_raw(PolicyCheckRequest(
        topic="job.x", metadata=JobMetadata(risk_tags=["destructive"])))
    assert resp.decision == "DENY"
    assert resp.remediations and resp.remediations[0].id == "strip-destructive"
    resp = await kernel.evaluate_raw(PolicyCheckRequest(
        topic="job.tpu.infer", metadata=JobMetadata(capability="tpu")))
    assert resp.decision == "ALLOW_WITH_CONSTRAINTS"
    assert resp.constraints.max_chips == 4
    # config overlay applied
    eff = await cs.effective()
    assert eff["rate_limits"]["concurrent_jobs"] == 8
    # registry records it
    assert "demo-guardrails" in await installer.list_installed()


async def test_simulation_failure_rolls_back(kv):
    installer, cs, kernel = make_installer(kv)
    await kernel.reload()
    doc = {
        "id": "badpack", "version": "1.0",
        "resources": {"workflows": [
            {"id": "bp-wf", "steps": {"s": {"topic": "job.t"}}}]},
        "overlays": {"policy": [{"id": "p", "fragment": {
            "enabled": True,
            "rules": [{"id": "r", "match": {"topics": ["job.z"]}, "decision": "allow"}]}}]},
        "simulations": [{"name": "must-deny", "request": {"topic": "job.z"}, "expect": "DENY"}],
    }
    with pytest.raises(PackError, match="must-deny"):
        await installer.install(manifest_from_doc(doc))
    # everything rolled back
    assert await installer.wf_store.get_workflow("bp-wf") is None
    assert await cs.get("system", "policy/badpack/p") is None
    assert "badpack" not in await installer.list_installed()


async def test_uninstall_removes_resources(kv):
    installer, cs, kernel = make_installer(kv)
    await kernel.reload()
    m = load_pack_dir(f"{REPO}/examples/demo-guardrails")
    await installer.install(m)
    assert await installer.uninstall("demo-guardrails")
    assert await installer.wf_store.get_workflow("guarded-inference") is None
    resp = await kernel.evaluate_raw(PolicyCheckRequest(
        topic="job.x", metadata=JobMetadata(risk_tags=["destructive"])))
    assert resp.decision == "ALLOW"  # fragment gone
    assert not await installer.uninstall("demo-guardrails")  # idempotent


async def test_install_invalid_workflow_rejected(kv):
    installer, cs, kernel = make_installer(kv)
    doc = {"id": "p1", "resources": {"workflows": [
        {"id": "w", "steps": {"a": {"topic": "t", "depends_on": ["missing"]}}}]}}
    with pytest.raises(PackError, match="unknown dependency"):
        await installer.install(manifest_from_doc(doc))


async def test_pack_catalogs(kv, tmp_path):
    import os

    from cordum_tpu.packs import PackCatalog, PackError

    installer, cs, kernel = make_installer(kv)
    await kernel.reload()
    cat = PackCatalog(cs, installer)
    # allowed-roots gating
    await cat.set_allowed_roots([str(tmp_path)])
    with pytest.raises(PackError, match="outside allowed roots"):
        await cat.add_catalog("bad", REPO + "/examples")
    # build a local catalog inside the allowed root
    import shutil

    shutil.copytree(f"{REPO}/examples/hello-pack", str(tmp_path / "hello-pack"))
    await cat.add_catalog("local", str(tmp_path))
    packs = await cat.list_packs("local")
    assert packs and packs[0]["id"] == "hello-pack"
    record = await cat.install_from_catalog("local", "hello-pack")
    assert "hello-pack-echo" in record["workflows"]
    with pytest.raises(PackError, match="not found"):
        await cat.install_from_catalog("local", "nope")


async def test_pack_catalog_root_boundaries(kv, tmp_path):
    """Prefix tricks and symlink escapes must not pass the allowed-roots gate
    (advisor finding: plain startswith let /opt/packs-evil match /opt/packs)."""
    import os

    from cordum_tpu.packs import PackCatalog, PackError

    installer, cs, kernel = make_installer(kv)
    cat = PackCatalog(cs, installer)
    good = tmp_path / "packs"
    good.mkdir()
    evil = tmp_path / "packs-evil"  # same string prefix, different dir
    evil.mkdir()
    outside = tmp_path / "outside"
    outside.mkdir()
    link = good / "escape"  # symlink inside the root pointing out of it
    os.symlink(str(outside), str(link))
    await cat.set_allowed_roots([str(good)])
    with pytest.raises(PackError, match="outside allowed roots"):
        await cat.add_catalog("evil", str(evil))
    with pytest.raises(PackError, match="outside allowed roots"):
        await cat.add_catalog("escape", str(link))
    # the root itself and true subdirectories still pass
    (good / "sub").mkdir()
    await cat.add_catalog("root", str(good))
    await cat.add_catalog("sub", str(good / "sub"))


async def test_pack_catalog_http(tmp_path):
    import shutil

    from tests.test_gateway import GwStack

    shutil.copytree(f"{REPO}/examples/hello-pack", str(tmp_path / "hello-pack"))
    async with GwStack() as s:
        r = await s.client.post("/api/v1/pack-catalogs",
                                json={"name": "local", "path": str(tmp_path),
                                      "allowed_roots": [str(tmp_path)]},
                                headers=s.h(admin=True))
        assert r.status == 201
        r = await s.client.get("/api/v1/pack-catalogs/local/packs", headers=s.h())
        assert (await r.json())["packs"][0]["id"] == "hello-pack"
        r = await s.client.post("/api/v1/pack-catalogs/local/install/hello-pack",
                                headers=s.h(admin=True))
        assert r.status == 201
        r = await s.client.get("/api/v1/packs", headers=s.h())
        assert "hello-pack" in (await r.json())["packs"]


async def test_pack_http_endpoints():
    from tests.test_gateway import GwStack

    async with GwStack() as s:
        m = load_pack_dir(f"{REPO}/examples/hello-pack")
        doc = {"id": m.id, "version": m.version,
               "resources": {"workflows": m.workflows, "schemas": m.schemas},
               "overlays": {"config": m.config_overlays, "policy": m.policy_overlays},
               "simulations": m.simulations}
        r = await s.client.post("/api/v1/packs", json=doc, headers=s.h())
        assert r.status == 403  # non-admin
        r = await s.client.post("/api/v1/packs", json=doc, headers=s.h(admin=True))
        assert r.status == 201
        r = await s.client.get("/api/v1/packs", headers=s.h())
        assert "hello-pack" in (await r.json())["packs"]
        r = await s.client.get("/api/v1/workflows/hello-pack-echo", headers=s.h())
        assert r.status == 200
        r = await s.client.delete("/api/v1/packs/hello-pack", headers=s.h(admin=True))
        assert (await r.json())["uninstalled"]
