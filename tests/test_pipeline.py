"""KV pipeline semantics (ISSUE 4): atomic multi-op commits, version-watched
chains with conflict retry, partial-failure behavior, the wire-level PIPE
frame, and the engine hot path rebuilt on pipelined commits.

The `statebus`-marked tests run against a LIVE TCP StateBusServer — CI runs
them as a dedicated step so the hot path can't silently regress to per-op
wire calls.
"""
import asyncio
import contextlib

import pytest

from cordum_tpu.controlplane.safetykernel.kernel import SafetyKernel
from cordum_tpu.controlplane.scheduler.engine import Engine
from cordum_tpu.controlplane.scheduler.safety_client import SafetyClient
from cordum_tpu.controlplane.scheduler.strategy import LeastLoadedStrategy
from cordum_tpu.infra.bus import LoopbackBus, MAX_REDELIVERIES, RetryAfter
from cordum_tpu.infra.config import parse_pool_config
from cordum_tpu.infra.jobstore import JobStore, MetaSnapshot
from cordum_tpu.infra.kv import MemoryKV, PIPELINE_OPS
from cordum_tpu.infra.metrics import Metrics
from cordum_tpu.infra.registry import WorkerRegistry
from cordum_tpu.infra.statebus import StateBusServer, connect
from cordum_tpu.protocol import subjects as subj
from cordum_tpu.protocol.types import BusPacket, Heartbeat, JobRequest, JobResult, JobState

BACKENDS = ("memory", "statebus")


@contextlib.asynccontextmanager
async def kv_backend(kind):
    if kind == "memory":
        yield MemoryKV()
        return
    srv = StateBusServer(port=0)
    await srv.start()
    kv, _bus, conn = await connect(f"statebus://127.0.0.1:{srv.port}")
    try:
        yield kv
    finally:
        await conn.close()
        await srv.stop()


# ------------------------------------------------------------- pipeline core

@pytest.mark.parametrize("kind", BACKENDS)
async def test_pipeline_atomic_multi_op(kind):
    """Every op kind in one batch, applied atomically in one round trip."""
    async with kv_backend(kind) as kv:
        await kv.set("gone", b"x")
        await kv.set("guard", b"me")
        await kv.rpush("l", b"a", b"b", b"c", b"d")
        p = kv.pipeline()
        p.hset("h", {"f": b"1"}).hdel("h", "missing")
        p.zadd("z", "m1", 1.0).zrem("z", "nope")
        p.rpush("l", b"e").ltrim("l", -3, -1)
        p.sadd("s", "a", "b")
        p.set("k", b"v", 60.0).expire("h", 60.0)
        p.delete("gone").del_eq("guard", b"me")
        assert await p.execute() is True
        assert await kv.hgetall("h") == {"f": b"1"}
        assert await kv.zrange("z") == ["m1"]
        assert await kv.lrange("l") == [b"c", b"d", b"e"]
        assert await kv.smembers("s") == {"a", "b"}
        assert await kv.get("k") == b"v"
        assert await kv.get("gone") is None
        assert await kv.get("guard") is None


@pytest.mark.parametrize("kind", BACKENDS)
async def test_pipeline_watch_semantics(kind):
    """Version watches: version-0 means key-absent; any concurrent mutation
    aborts the whole batch; new_versions supports read-free chaining."""
    async with kv_backend(kind) as kv:
        p = kv.pipeline().hset("w", {"a": b"1"})
        p.watch("w", 0)  # key must not exist yet
        assert await p.execute() is True
        ver = p.new_versions["w"]
        assert ver > 0
        # chained commit using the returned version: no re-read needed
        p2 = kv.pipeline().hset("w", {"b": b"2"})
        p2.watch("w", ver)
        assert await p2.execute() is True
        # stale version → conflict, nothing applied
        p3 = kv.pipeline().hset("w", {"c": b"3"}).set("other", b"x")
        p3.watch("w", ver)
        assert await p3.execute() is False
        h = await kv.hgetall("w")
        assert "c" not in h and await kv.get("other") is None


@pytest.mark.parametrize("kind", BACKENDS)
async def test_pipeline_conflict_retry_under_concurrent_writers(kind):
    """Two optimistic writers increment one hash field through watched
    pipelines; conflicts force re-reads and no increment is lost."""
    async with kv_backend(kind) as kv:
        async def incr(n):
            for _ in range(n):
                while True:
                    ver, h = await kv.watch_read("counter")
                    cur = int(h.get("n", b"0"))
                    p = kv.pipeline().hset("counter", {"n": str(cur + 1).encode()})
                    p.watch("counter", ver)
                    if await p.execute():
                        break
        await asyncio.gather(incr(30), incr(30))
        _, h = await kv.watch_read("counter")
        assert int(h["n"]) == 60


@pytest.mark.parametrize("kind", BACKENDS)
async def test_pipeline_unknown_op_rejects_whole_batch(kind):
    """Partial-failure behavior: ops are validated before anything applies —
    an unknown op rejects the WHOLE batch and leaves state untouched."""
    async with kv_backend(kind) as kv:
        with pytest.raises((ValueError, RuntimeError)):
            await kv.pipe_execute({}, [("hset", "h", {"a": b"1"}), ("bogus", "x")])
        assert await kv.hgetall("h") == {}
        # the buffered builder rejects unknown ops client-side too
        with pytest.raises(ValueError):
            kv.pipeline().op("hincrby", "h", "a")
        assert "hincrby" not in PIPELINE_OPS


@pytest.mark.statebus
async def test_pipe_wire_frame_roundtrip():
    """Wire level: one PIPE frame carries the whole batch and gets one
    [ok, new_versions] reply — exactly one wire round trip per execute."""
    srv = StateBusServer(port=0)
    await srv.start()
    kv, _bus, conn = await connect(f"statebus://127.0.0.1:{srv.port}")
    try:
        m = Metrics()
        kv.bind_metrics(m)
        # raw frame through the shared connection
        ok, versions = await conn.call(
            "pipe", {"k": 0}, [["set", "k", b"v", None], ["zadd", "z", "m", 1.0]]
        )
        assert ok is True and versions["k"] > 0
        # a client Pipeline.execute() is exactly one counted round trip
        p = kv.pipeline().hset("h", {"a": b"1"}).zadd("z2", "m", 2.0)
        p.watch("h", 0)
        assert await p.execute() is True
        assert m.kv_roundtrips.value(op="pipe") == 1
        assert m.kv_roundtrips.total() == 1  # no hidden per-op calls
        assert m.kv_pipeline_size.quantile(0.5) >= 2
        # conflict surfaces as ok=False in the same single reply
        ok2, _ = await conn.call("pipe", {"h": 0}, [["set", "never", b"x", None]])
        assert ok2 is False
        assert await kv.get("never") is None
        # server-side observability saw the pipe op
        text = await kv.server_metrics()
        assert 'cordum_statebus_op_seconds_count{op="pipe"}' in text
    finally:
        await conn.close()
        await srv.stop()


# ------------------------------------------------------------ jobstore chains

@pytest.mark.parametrize("kind", BACKENDS)
async def test_jobstore_apply_chain_matches_serial_transitions(kind):
    """A DISPATCHED→RUNNING chain in one commit produces the same meta,
    indexes and event log as two serial set_state calls."""
    async with kv_backend(kind) as kv:
        js = JobStore(kv)
        # serial reference
        await js.set_state("ser", JobState.PENDING, fields={"tenant_id": "t"})
        await js.set_state("ser", JobState.SCHEDULED, event="scheduled")
        await js.set_state("ser", JobState.DISPATCHED, event="dispatched")
        await js.set_state("ser", JobState.RUNNING, event="running")
        # chained
        _, snap = await js.apply_chain(
            "ch", [(JobState.PENDING, {"tenant_id": "t"}, "")], snap=MetaSnapshot()
        )
        _, snap = await js.apply_chain(
            "ch",
            [(JobState.SCHEDULED, None, "scheduled"),
             (JobState.DISPATCHED, None, "dispatched"),
             (JobState.RUNNING, None, "running")],
            snap=snap,
        )
        assert await js.get_state("ch") == "RUNNING"
        ser_events = [e["event"] for e in await js.events("ser")]
        ch_events = [e["event"] for e in await js.events("ch")]
        assert ch_events == ser_events
        assert set(await js.list_by_state("RUNNING")) == {"ch", "ser"}
        for st in ("PENDING", "SCHEDULED", "DISPATCHED"):
            assert await js.list_by_state(st) == []
        # terminal chain clears deadline + tenant membership atomically
        await js.register_deadline("ch", 123)
        await js.tenant_active_add("t", "ch")
        _, snap = await js.apply_chain(
            "ch", [(JobState.SUCCEEDED, None, "result")], snap=snap
        )
        assert await js.expired_deadlines(10_000) == []
        assert await js.tenant_active_count("t") == 0
        assert (await js.get_meta("ch"))["finished_at_us"]


async def test_jobstore_snapshot_chaining_needs_no_rereads():
    """The optimistic snapshot thread means a whole job lifecycle costs one
    pipelined commit per transition group and ZERO extra read round trips."""
    kv = MemoryKV()
    m = Metrics()
    kv.bind_metrics(m)
    js = JobStore(kv)
    _, snap = await js.apply_chain(
        "j", [(JobState.PENDING, {"topic": "t"}, "submit")], snap=MetaSnapshot()
    )
    _, snap = await js.apply_chain("j", [(JobState.SCHEDULED, None, "")], snap=snap)
    _, snap = await js.apply_chain(
        "j",
        [(JobState.DISPATCHED, None, ""), (JobState.RUNNING, None, "")],
        snap=snap,
    )
    assert await js.get_state("j") == "RUNNING"  # 1 hget (not counted below)
    assert m.kv_roundtrips.value(op="pipe") == 3
    assert m.kv_roundtrips.value(op="watch_read") == 0


async def test_jobstore_apply_chain_conflict_rereads_and_retries():
    kv = MemoryKV()
    js = JobStore(kv)
    await js.set_state("j", JobState.PENDING)
    stale = MetaSnapshot(1, {"state": b"PENDING"})  # wrong version on purpose
    changed, snap = await js.apply_chain(
        "j", [(JobState.SCHEDULED, None, "")], snap=stale
    )
    assert changed is True and snap.state == "SCHEDULED"
    # exhausted retries surface as changed=None with a fresh snapshot
    changed, snap = await js.apply_chain(
        "j", [(JobState.DISPATCHED, None, "")],
        snap=MetaSnapshot(10**9, {"state": b"SCHEDULED"}), max_retries=1,
    )
    assert changed is None and snap.state == "SCHEDULED"


async def test_job_lock_release_is_compare_and_delete():
    kv = MemoryKV()
    js = JobStore(kv)
    assert await js.acquire_job_lock("j", "owner-a")
    await js.release_job_lock("j", "owner-b")  # wrong owner: no-op
    assert not await js.acquire_job_lock("j", "owner-b")
    await js.release_job_lock("j", "owner-a")
    assert await js.acquire_job_lock("j", "owner-b")


# --------------------------------------------------------------- bus redelivery

async def test_hot_nak_cycle_is_iterative_and_capped(monkeypatch):
    """A zero-delay NAK cycle redelivers MAX_REDELIVERIES times without
    growing the stack, and huge RetryAfter delays are capped."""
    import cordum_tpu.infra.bus as busmod

    delays = []

    async def fake_sleep(d):
        delays.append(d)

    monkeypatch.setattr(busmod.asyncio, "sleep", fake_sleep)
    bus = LoopbackBus()
    attempts = []
    depths = []

    async def h(subject, pkt):
        import inspect

        attempts.append(1)
        depths.append(len(inspect.stack()))
        raise RetryAfter(9999.0)

    await bus.subscribe("sys.job.submit", h, queue="g")
    await bus.publish("sys.job.submit", BusPacket.wrap(JobRequest(job_id="j", topic="t")))
    await bus.drain()
    assert len(attempts) == MAX_REDELIVERIES
    assert len(set(depths)) == 1  # constant stack depth: iterative, not recursive
    assert delays and all(d == busmod.MAX_NAK_DELAY_S for d in delays)
    await bus.close()


# --------------------------------------------------------------- engine level

def _make_engine(kv, bus):
    js = JobStore(kv)
    kernel = SafetyKernel(policy_doc={})
    reg = WorkerRegistry()
    reg.update(Heartbeat(worker_id="w1", pool="default", max_parallel_jobs=1 << 30))
    pc = parse_pool_config({"topics": {"job.default": "default"}, "pools": {"default": {}}})
    eng = Engine(
        bus=bus, job_store=js, safety=SafetyClient(kernel.check),
        strategy=LeastLoadedStrategy(reg, pc), registry=reg,
    )
    return eng, js


async def _worker_echo(bus):
    async def handler(subject, pkt):
        req = pkt.job_request
        await bus.publish(
            subj.RESULT,
            BusPacket.wrap(
                JobResult(job_id=req.job_id, status="SUCCEEDED", worker_id="w1"),
                sender_id="w1",
            ),
        )

    await bus.subscribe(subj.direct_subject("w1"), handler, queue="w")


async def test_engine_concurrent_burst_matches_serial():
    """64 jobs submitted concurrently under the bounded semaphore produce
    exactly the same terminal states and event logs as serial processing."""
    n = 64

    # serial reference: one job at a time straight through the engine
    kv_s = MemoryKV()
    bus_s = LoopbackBus(sync=True)
    eng_s, js_s = _make_engine(kv_s, bus_s)
    for i in range(n):
        await eng_s.handle_job_request(JobRequest(job_id=f"j{i}", topic="job.default"))
        await eng_s.handle_job_result(JobResult(job_id=f"j{i}", status="SUCCEEDED"))
    serial = {}
    for i in range(n):
        serial[f"j{i}"] = (
            await js_s.get_state(f"j{i}"),
            [e["event"] for e in await js_s.events(f"j{i}")],
        )

    # concurrent burst through the async bus
    kv_c = MemoryKV()
    bus_c = LoopbackBus()
    eng_c, js_c = _make_engine(kv_c, bus_c)
    await eng_c.start()
    await _worker_echo(bus_c)
    for i in range(n):
        await bus_c.publish(
            subj.SUBMIT,
            BusPacket.wrap(JobRequest(job_id=f"j{i}", topic="job.default")),
        )
    for _ in range(200):
        await bus_c.drain()
        if eng_c.metrics.jobs_completed.value(status="SUCCEEDED") >= n:
            break
        await asyncio.sleep(0.01)
    await eng_c.stop()
    for i in range(n):
        jid = f"j{i}"
        state = await js_c.get_state(jid)
        events = [e["event"] for e in await js_c.events(jid)]
        assert (state, events) == serial[jid], jid
    await bus_c.close()
    await bus_s.close()


@pytest.mark.statebus
async def test_pipe_commits_survive_aof_replay(tmp_path):
    """Pipelined commits are AOF-logged as single atomic entries and replay
    on restart — the crash-safe-state guarantee holds for the new frame."""
    aof = str(tmp_path / "state.aof")
    srv = StateBusServer(port=0, aof_path=aof)
    await srv.start()
    kv, _bus, conn = await connect(f"statebus://127.0.0.1:{srv.port}")
    js = JobStore(kv)
    _, snap = await js.apply_chain(
        "j1", [(JobState.PENDING, {"topic": "t"}, "submit")], snap=MetaSnapshot()
    )
    _, snap = await js.apply_chain(
        "j1",
        [(JobState.SCHEDULED, None, "scheduled"),
         (JobState.DISPATCHED, None, "dispatched"),
         (JobState.RUNNING, None, "running")],
        snap=snap,
    )
    await conn.close()
    await srv.stop()
    srv2 = StateBusServer(port=0, aof_path=aof)
    await srv2.start()
    kv2, _bus2, conn2 = await connect(f"statebus://127.0.0.1:{srv2.port}")
    try:
        js2 = JobStore(kv2)
        assert await js2.get_state("j1") == "RUNNING"
        assert [e["event"] for e in await js2.events("j1")] == [
            "submit", "scheduled", "dispatched", "running",
        ]
        assert await js2.list_by_state("RUNNING") == ["j1"]
    finally:
        await conn2.close()
        await srv2.stop()


@pytest.mark.statebus
async def test_engine_hot_path_is_pipelined_over_live_statebus():
    """End-to-end over a REAL TCP statebus: a 20-job burst completes, the
    submit→result path stays under a hard per-job wire-round-trip budget,
    and every state mutation rides PIPE frames — the regression guard that
    keeps the hot path from decaying to per-op calls.  (Tick batching folds
    several jobs' commits into ONE pipe, so the guard is on the per-op
    mutation count, not a pipes-per-job floor.)
    """
    srv = StateBusServer(port=0)
    await srv.start()
    url = f"statebus://127.0.0.1:{srv.port}"
    skv, sbus, sconn = await connect(url)  # scheduler process
    wkv, wbus, wconn = await connect(url)  # worker process
    try:
        eng, js = _make_engine(skv, sbus)
        await eng.start()
        await _worker_echo(wbus)
        n = 20
        for i in range(n):
            await sbus.publish(
                subj.SUBMIT,
                BusPacket.wrap(JobRequest(job_id=f"j{i}", topic="job.default")),
            )
        for _ in range(400):
            if eng.metrics.jobs_completed.value(status="SUCCEEDED") >= n:
                break
            await asyncio.sleep(0.02)
        assert eng.metrics.jobs_completed.value(status="SUCCEEDED") == n
        for i in range(n):
            assert await js.get_state(f"j{i}") == "SUCCEEDED"
        # wire budget: submit→DISPATCHED→RUNNING→result used to cost ~20+
        # round trips per job; pipelined it must stay in single digits
        per_job = eng.metrics.kv_roundtrips.total() / n
        assert per_job <= 10.0, f"kv round-trips/job regressed to {per_job:.1f}"
        pipes = eng.metrics.kv_roundtrips.value(op="pipe")
        assert pipes >= 3.0, "hot path no longer rides PIPE frames"
        # per-op mutating calls must stay off the hot path: everything the
        # lifecycle writes (meta, indexes, events, records) rides a pipe
        mutating = sum(
            eng.metrics.kv_roundtrips.value(op=op)
            for op in ("set", "hset", "zadd", "zrem", "rpush", "ltrim", "sadd")
        )
        assert mutating == 0, f"{mutating} per-op mutations leaked off the PIPE path"
        await eng.stop()
    finally:
        await sconn.close()
        await wconn.close()
        await srv.stop()
