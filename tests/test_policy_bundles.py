"""Policy bundle admin: staged writes, publish/unpublish, draft simulation,
snapshot capture/rollback, audit trail — library + HTTP."""
import pytest

from cordum_tpu.controlplane.safetykernel.bundles import PolicyBundleAdmin, unescape_bundle_id
from cordum_tpu.controlplane.safetykernel.kernel import SafetyKernel
from cordum_tpu.infra.configsvc import ConfigService
from cordum_tpu.infra.kv import MemoryKV
from cordum_tpu.protocol.types import PolicyCheckRequest

BASE = {"tenants": {"default": {"allow_topics": ["job.*", "job.>"]}}}

DENY_BUNDLE = {"rules": [{"id": "no-x", "match": {"topics": ["job.x"]}, "decision": "deny"}]}


async def make_admin(kv):
    cs = ConfigService(kv)
    kernel = SafetyKernel(policy_doc=BASE, configsvc=cs)
    await kernel.reload()
    return PolicyBundleAdmin(kv, cs, kernel), kernel


async def test_staged_bundle_then_publish(kv):
    admin, kernel = await make_admin(kv)
    await admin.put_bundle("team/deny-x", DENY_BUNDLE, actor="alice")
    # staged: disabled → no effect yet
    resp = await kernel.evaluate_raw(PolicyCheckRequest(topic="job.x"))
    assert resp.decision == "ALLOW"
    bundles = await admin.list_bundles()
    assert bundles[0]["bundle_id"] == "team/deny-x" and not bundles[0]["enabled"]
    # publish → active
    result = await admin.publish("team/deny-x", actor="alice")
    assert result["enabled"]
    resp = await kernel.evaluate_raw(PolicyCheckRequest(topic="job.x"))
    assert resp.decision == "DENY"
    # unpublish → inactive again
    await admin.unpublish("team/deny-x", actor="alice")
    resp = await kernel.evaluate_raw(PolicyCheckRequest(topic="job.x"))
    assert resp.decision == "ALLOW"
    audit = await admin.audit_log()
    assert [e["action"] for e in audit] == ["put_bundle", "publish", "unpublish"]
    assert all(e["actor"] == "alice" for e in audit)


async def test_draft_simulation_without_install(kv):
    admin, kernel = await make_admin(kv)
    results = await admin.simulate_draft(DENY_BUNDLE, [PolicyCheckRequest(topic="job.x")])
    assert results[0]["decision"] == "DENY"
    # live policy untouched
    resp = await kernel.evaluate_raw(PolicyCheckRequest(topic="job.x"))
    assert resp.decision == "ALLOW"


async def test_snapshot_capture_and_rollback(kv):
    admin, kernel = await make_admin(kv)
    await admin.put_bundle("good", {"enabled": True, "rules": []}, actor="a")
    cap = await admin.capture_snapshot(actor="a", note="before risky change")
    # risky change: a deny-everything bundle
    await admin.put_bundle(
        "risky", {"enabled": True,
                  "rules": [{"id": "all", "match": {"topics": ["job.>"]}, "decision": "deny"}]},
        actor="a",
    )
    assert (await kernel.evaluate_raw(PolicyCheckRequest(topic="job.any.thing"))).decision == "DENY"
    # rollback removes the bundle added after the capture
    result = await admin.rollback(cap["snapshot_id"], actor="a")
    assert result["rolled_back_to"] == cap["snapshot_id"]
    assert (await kernel.evaluate_raw(PolicyCheckRequest(topic="job.any.thing"))).decision == "ALLOW"
    assert await admin.get_bundle("good") is not None
    assert await admin.get_bundle("risky") is None
    snaps = await admin.list_captured()
    assert snaps and snaps[0]["note"] == "before risky change"


def test_bundle_id_escaping():
    assert unescape_bundle_id("team~deny-x") == "team/deny-x"


async def test_bundles_http():
    from tests.test_gateway import GwStack

    async with GwStack() as s:
        r = await s.client.put("/api/v1/policy/bundles/team~frag", json=DENY_BUNDLE, headers=s.h())
        assert r.status == 403
        r = await s.client.put("/api/v1/policy/bundles/team~frag", json=DENY_BUNDLE,
                               headers=s.h(admin=True))
        assert r.status == 201
        r = await s.client.get("/api/v1/policy/bundles", headers=s.h())
        assert (await r.json())["bundles"][0]["bundle_id"] == "team/frag"
        r = await s.client.post("/api/v1/policy/bundles/team~frag/simulate",
                                json={"requests": [{"topic": "job.x"}]}, headers=s.h())
        assert (await r.json())["results"][0]["decision"] == "DENY"
        r = await s.client.post("/api/v1/policy/bundles/team~frag/publish", headers=s.h(admin=True))
        assert (await r.json())["enabled"]
        r = await s.client.post("/api/v1/policy/snapshots/capture", json={"note": "n"},
                                headers=s.h(admin=True))
        snap_id = (await r.json())["snapshot_id"]
        r = await s.client.post(f"/api/v1/policy/snapshots/{snap_id}/rollback",
                                headers=s.h(admin=True))
        assert r.status == 200
        r = await s.client.get("/api/v1/policy/audit", headers=s.h())
        actions = [e["action"] for e in (await r.json())["audit"]]
        assert "publish" in actions and "rollback" in actions
