"""Prefix cache + session tiering (ISSUE 18, docs/SERVING.md §Prefix cache
and tiering): refcounted copy-on-write shared-prefix KV pages, radix-cache
admission hits, LRU eviction under exhaustion, and hibernate/restore through
the host-RAM cold arena — with the allocator's accounting property-tested
under random admit/share/CoW/free/hibernate interleavings and the real paged
backend pinned to the fp32 sequential oracle."""
import asyncio
import random
import time

import pytest

from cordum_tpu.serving.engine import (
    GenRequest,
    ServingEngine,
    SessionHibernated,
)
from cordum_tpu.serving.pager import (
    CacheExhausted,
    PageAccountingError,
    PageAllocator,
)
from cordum_tpu.serving.prefixcache import PrefixCache

from .test_serving import FakeBackend, run_blocking
from .test_serving_failover import wait_until

# ------------------------------------------------------- allocator refcounts


def test_refcount_share_lifecycle():
    a = PageAllocator(8, 4)
    p = a.alloc("s1", 3)
    a.retain([p[0]])
    assert a.refcount(p[0]) == 2 and a.stats.shares == 1
    assert a.free("s1") == 2  # the shared page survives under the extra ref
    assert a.refcount(p[0]) == 1 and a.free_pages == 6
    assert a.release([p[0]]) == 1
    assert a.free_pages == 7
    a.check_consistency()


def test_double_free_and_share_of_free_raise():
    a = PageAllocator(8, 4)
    p = a.alloc("s1", 2)
    a.free("s1")
    with pytest.raises(PageAccountingError):
        a.release([p[0]])  # double free fails loudly
    with pytest.raises(PageAccountingError):
        a.retain([p[1]])  # sharing a freed page would alias the free list
    assert a.free("s1") == 0  # unknown-owner free stays a benign no-op
    a.check_consistency()


def test_alloc_shared_all_or_nothing():
    a = PageAllocator(8, 4)  # capacity 7
    shared = a.alloc("cache", 2)
    with pytest.raises(CacheExhausted):
        a.alloc("s2", 6, shared=shared)
    assert a.refcount(shared[0]) == 1  # the failed admission touched nothing
    got = a.alloc("s2", 3, shared=shared)
    assert got[:2] == shared and len(got) == 5
    assert a.refcount(shared[0]) == 2
    assert a.free("s2") == 3  # fresh tail freed, shared prefix survives
    assert a.free("cache") == 2
    a.check_consistency()
    assert a.free_pages == a.capacity


def test_swap_owned_cow_bookkeeping():
    a = PageAllocator(8, 4)
    pages = a.alloc("s1", 2)
    (fresh,) = a.alloc_raw(1)
    a.swap_owned("s1", pages[1], fresh)
    a.release([pages[1]])  # the CoW path's release of the old page
    assert a.free("s1") == 2  # pages[0] + the swapped-in fresh page
    a.check_consistency()
    assert a.free_pages == a.capacity
    with pytest.raises(PageAccountingError):
        a.swap_owned("nobody", 1, 2)


def test_allocator_random_ops_property():
    """No interleaving of alloc/share/release/free ever leaves a page both
    free and referenced, a negative refcount, or a lost page."""
    rng = random.Random(7)
    a = PageAllocator(17, 4)
    owners: dict[str, list[int]] = {}
    cache: list[int] = []  # bare references (retain'd / alloc_raw'd)
    for step in range(2000):
        op = rng.random()
        if op < 0.35:
            name = f"o{step}"
            shared = (
                [rng.choice(cache) for _ in range(rng.randint(0, 2))]
                if cache else []
            )
            try:
                n = rng.randint(0 if shared else 1, 4)
                owners[name] = a.alloc(name, n, shared=shared)
                # the allocator added one ref per shared entry on top of the
                # cache's own — the owner's table now co-holds those pages
            except (CacheExhausted, ValueError):
                pass
        elif op < 0.55 and owners:
            name = rng.choice(list(owners))
            a.free(name)
            del owners[name]
        elif op < 0.7:
            live = [p for pages in owners.values() for p in pages]
            if live:
                p = rng.choice(live)
                a.retain([p])
                cache.append(p)
        elif op < 0.85 and cache:
            a.release([cache.pop(rng.randrange(len(cache)))])
        else:
            try:
                cache.extend(a.alloc_raw(rng.randint(1, 2)))
            except CacheExhausted:
                pass
        a.check_consistency(live_tables=owners)
    for name in list(owners):
        a.free(name)
    while cache:
        a.release([cache.pop()])
    a.check_consistency()
    assert a.free_pages == a.capacity


# ------------------------------------------------------------- radix cache


def test_radix_match_register_evict():
    a = PageAllocator(32, 4)
    c = PrefixCache(a)
    toks = list(range(1, 13))  # 12 tokens = 3 full pages
    pages = a.alloc("s1", 3)
    assert c.match(toks) == []
    assert c.register(toks, pages) == 3
    a.free("s1")
    assert a.used_pages == 3  # the cache's refs keep them off the free list
    assert [n.page for n in c.match(toks + [99])] == pages
    # a divergent suffix shares only the common full-page prefix
    assert [n.page for n in c.match(toks[:8] + [7, 7, 7, 7])] == pages[:2]
    # partial trailing page is never cached
    assert c.register(toks[:6], a.alloc("s2", 2)) == 0
    a.free("s2")
    assert c.evict(2) == 2 and a.used_pages == 1  # LRU leaves first
    c.evict(5)
    assert a.used_pages == 0 and c.warm_pages == 0
    a.check_consistency()


def test_evict_skips_pages_shared_with_live_sessions():
    a = PageAllocator(32, 4)
    c = PrefixCache(a)
    toks = list(range(1, 13))
    pages = a.alloc("s1", 3)
    c.register(toks, pages)
    a.free("s1")
    a.retain([pages[0]])  # a live session still maps the first page
    assert c.evict(3) == 2  # the shared root is not evictable
    assert a.refcount(pages[0]) == 2 and a.used_pages == 1
    a.release([pages[0]])
    a.release([pages[0]])
    a.check_consistency()


def test_demote_promote_roundtrip():
    a = PageAllocator(16, 4)
    c = PrefixCache(a)
    toks = [5, 6, 7, 8]
    pages = a.alloc("s1", 1)
    c.register(toks, pages)
    a.free("s1")
    (node,) = c.match(toks)
    # demote refuses while a live sharer holds the page
    a.retain([node.page])
    assert c.demote(node, {"i": 0, "k": [5, 6, 7, 8]}) is False
    a.release([node.page])
    assert c.demote(node, {"i": 0, "k": [5, 6, 7, 8]}) is True
    assert node.cold and a.used_pages == 0 and c.cold_pages == 1
    # the cold node still matches; promote re-warms it onto a fresh page
    (again,) = c.match(toks)
    assert again is node
    (fresh,) = a.alloc_raw(1)
    c.promote(node, fresh)
    assert node.warm and c.warm_pages == 1
    c.evict(1)
    a.check_consistency()


# --------------------------------------------- engine (arena-modeling fake)


class ArenaFakeBackend(FakeBackend):
    """FakeBackend + a host-integer 'arena': page contents are real state,
    samples read the FULL written prefix through the page table, and
    copy_page / export_kv / import_kv move actual slots — so prefix
    sharing, CoW, and hibernate bugs change emitted tokens instead of
    hiding behind per-session accumulators."""

    def __init__(self, **kw):
        super().__init__(**kw)
        self.arena: dict[int, list[int]] = {}
        self.copies = 0
        self.fed_prefill: dict[str, int] = {}  # key -> prompt tokens fed

    def _row(self, page):
        return self.arena.setdefault(page, [0] * self.page_size)

    def _read(self, pages, n):
        ps = self.page_size
        return [self._row(pages[i // ps])[i % ps] for i in range(n)]

    @staticmethod
    def _sample(seq):
        return (sum(seq) * 3 + len(seq)) % 251

    def step(self, entries):
        import time as _t

        if self.step_delay:
            _t.sleep(self.step_delay)
        assert len(entries) <= self.max_seqs, "max_seqs exceeded"
        assert sum(len(e.tokens) for e in entries) <= self.max_batch_tokens, \
            "flat token budget exceeded"
        self.last_step_compiled = self.steps == 0
        self.steps += 1
        self.decode_batches.append(len(entries))
        ps = self.page_size
        out = []
        for e in entries:
            for i, t in enumerate(e.tokens):
                pos = e.start + i
                self._row(e.pages[pos // ps])[pos % ps] = t
            written = e.start + len(e.tokens)
            if e.phase == "prefill":
                self.prefill_chunks += 1
                self.fed_prefill[e.key] = (
                    self.fed_prefill.get(e.key, 0) + len(e.tokens)
                )
                if e.sample:
                    self.prefills += 1
                    out.append(self._sample(self._read(e.pages, written)))
                else:
                    out.append(None)
            else:
                out.append(self._sample(self._read(e.pages, written)))
        return out

    def copy_page(self, src, dst):
        self.arena[dst] = list(self._row(src))
        self.copies += 1

    def export_kv(self, pages, start_tok, end_tok):
        if end_tok <= start_tok:
            return []
        ps = self.page_size
        first, last = start_tok // ps, -(-end_tok // ps)
        recs = []
        for o in range(first, min(last, len(pages))):
            used = min(ps, end_tok - o * ps)
            recs.append({"i": o, "used": used,
                         "k": list(self._row(pages[o])[:used]), "v": [],
                         "shape": [used]})
        return recs

    def import_kv(self, pages, records):
        ps = self.page_size
        for rec in records:
            row = [0] * ps
            for j, t in enumerate(rec["k"]):
                row[j] = t
            self.arena[pages[rec["i"]]] = row


def arena_ref(prompt, n_new):
    """Sequential oracle for ArenaFakeBackend: each sample is a function of
    the entire written prefix, so any aliasing corruption diverges."""
    seq = list(prompt)
    out = [ArenaFakeBackend._sample(seq)]
    for _ in range(n_new - 1):
        seq.append(out[-1])
        out.append(ArenaFakeBackend._sample(seq))
    return out


class Tap:
    """Token-stream sink asserting exactly-once delivery: the engine emits
    (tokens, end_offset, done); replays must agree with what streamed."""

    def __init__(self):
        self.buf: list[int] = []

    async def __call__(self, tokens, end_offset, done):
        start = end_offset - len(tokens)
        for i, t in enumerate(tokens):
            idx = start + i
            if idx == len(self.buf):
                self.buf.append(int(t))
            elif idx < len(self.buf):
                assert self.buf[idx] == int(t), (
                    f"replayed token diverges at {idx}: {self.buf[idx]} vs {t}")
            else:
                raise AssertionError(f"gap in stream at {idx}")


async def test_prefix_cache_requires_cow_capability():
    """Arena-less backends can neither share page contents nor duplicate
    them on divergent write: the cache must stay off entirely."""
    eng = ServingEngine(FakeBackend(), run_blocking=run_blocking)
    assert eng.prefix is None and eng.tiering is None
    await eng.stop()


async def test_prefix_hit_skips_prefill_token_identical():
    be = ArenaFakeBackend(num_pages=32, page_size=4, max_context=128)
    eng = ServingEngine(be, run_blocking=run_blocking, max_new_tokens_cap=64)
    assert eng.prefix is not None
    prompt = [9, 2, 7, 1, 8, 3, 5, 4, 6]  # two full pages + one token
    out1 = await asyncio.wait_for(eng.submit(
        GenRequest(prompt=prompt, max_new_tokens=6, stream=False),
        job_id="a"), timeout=20)
    assert out1["tokens"] == arena_ref(prompt, 6)
    assert eng.stats.prefix_misses == 1 and be.fed_prefill["a"] == len(prompt)
    out2 = await asyncio.wait_for(eng.submit(
        GenRequest(prompt=prompt, max_new_tokens=6, stream=False),
        job_id="b"), timeout=20)
    # token-identical to the no-sharing run, with the shared pages' prefill
    # skipped: only the post-divergence token crosses the device
    assert out2["tokens"] == out1["tokens"]
    assert eng.stats.prefix_hits == 1
    assert eng.stats.prefix_hit_tokens == 8
    assert be.fed_prefill["b"] == len(prompt) - 8
    eng.allocator.check_consistency()
    await eng.stop()


async def test_page_aligned_hit_cow_protects_shared_page():
    """A prompt that is an exact page multiple backs its hit up one token;
    re-feeding the final token writes into shared territory, which the CoW
    guard must copy — the cached page stays byte-identical for later hits."""
    be = ArenaFakeBackend(num_pages=32, page_size=4, max_context=128)
    eng = ServingEngine(be, run_blocking=run_blocking, max_new_tokens_cap=64)
    prompt = [11, 3, 7, 2, 9, 5, 8, 1]  # exactly two pages
    out1 = await asyncio.wait_for(eng.submit(
        GenRequest(prompt=prompt, max_new_tokens=5, stream=False),
        job_id="a"), timeout=20)
    cached = [n.page for n in eng.prefix.match(prompt, touch=False)]
    snapshot = [list(be.arena[p]) for p in cached]
    out2 = await asyncio.wait_for(eng.submit(
        GenRequest(prompt=prompt, max_new_tokens=5, stream=False),
        job_id="b"), timeout=20)
    assert out2["tokens"] == out1["tokens"] == arena_ref(prompt, 5)
    assert eng.stats.prefix_hits == 1 and eng.stats.prefix_hit_tokens == 7
    assert be.copies >= 1 and eng.stats.cow_copies >= 1
    # the shared pages the cache holds were never scribbled on
    assert [list(be.arena[p]) for p in cached] == snapshot
    out3 = await asyncio.wait_for(eng.submit(
        GenRequest(prompt=prompt, max_new_tokens=5, stream=False),
        job_id="c"), timeout=20)
    assert out3["tokens"] == out1["tokens"] and eng.stats.prefix_hits == 2
    eng.allocator.check_consistency()
    await eng.stop()


async def test_exhaustion_lru_evicts_cached_prefixes():
    be = ArenaFakeBackend(num_pages=8, page_size=4, max_context=128,
                          max_batch_tokens=64)
    eng = ServingEngine(be, run_blocking=run_blocking, max_new_tokens_cap=64)
    p_old = list(range(1, 17))       # 16 tokens: 4 full pages when cached
    p_new = list(range(101, 117))    # distinct: a miss that needs room
    out = await asyncio.wait_for(eng.submit(
        GenRequest(prompt=p_old, max_new_tokens=4, stream=False),
        job_id="old"), timeout=20)
    assert out["tokens"] == arena_ref(p_old, 4)
    cached = eng.prefix.warm_pages
    assert cached >= 4
    # footprint 5 > free pages: admission LRU-evicts the cache's pages
    # instead of parking in the admission queue forever
    out = await asyncio.wait_for(eng.submit(
        GenRequest(prompt=p_new, max_new_tokens=4, stream=False),
        job_id="new"), timeout=20)
    assert out["tokens"] == arena_ref(p_new, 4)
    assert eng.prefix.stats.evicted_pages >= 1
    eng.allocator.check_consistency()
    await eng.stop()


async def test_turn_hibernate_restore_roundtrip():
    """A finished conversation's cached pages demote to host-RAM records on
    the idle sweep (device pages freed), and the next turn re-warms them —
    token-identical to never having hibernated, with the tier accounting
    and worker hooks following along."""
    be = ArenaFakeBackend(num_pages=32, page_size=4, max_context=128)
    eng = ServingEngine(be, run_blocking=run_blocking, max_new_tokens_cap=64,
                        hibernate_after_s=30.0)
    events: list[tuple[str, str]] = []
    eng.tiering.on_hibernated = lambda k: events.append(("hibernated", k))
    eng.tiering.on_restored = lambda k: events.append(("restored", k))
    prompt = [4, 8, 2, 6, 1, 9]
    out1 = await asyncio.wait_for(eng.submit(
        GenRequest(prompt=prompt, max_new_tokens=7, stream=False,
                   session_key="conv"),
        job_id="t1"), timeout=20)
    assert out1["tokens"] == arena_ref(prompt, 7)
    warm = eng.prefix.warm_pages
    assert warm >= 2 and eng.tiering.resident_sessions == 1
    assert eng.tiering.tier_counts() == (1, 0)
    demoted = await eng.tiering.sweep(now=time.monotonic() + 60)
    assert demoted == warm
    assert eng.prefix.warm_pages == 0 and eng.prefix.cold_pages == warm
    assert eng.allocator.used_pages == 0  # device arena fully released
    assert eng.tiering.tier_counts() == (0, 1)
    assert events == [("hibernated", "conv")]
    # next turn: history + new suffix — the cold path restores, then hits
    p2 = prompt + out1["tokens"] + [42]
    out2 = await asyncio.wait_for(eng.submit(
        GenRequest(prompt=p2, max_new_tokens=4, stream=False,
                   session_key="conv"),
        job_id="t2"), timeout=20)
    assert out2["tokens"] == arena_ref(p2, 4)
    assert eng.stats.prefix_hits == 1
    assert eng.prefix.stats.restored_pages >= warm
    assert ("restored", "conv") in events
    eng.allocator.check_consistency()
    await eng.stop()


async def test_live_hibernate_restore_exactly_once():
    """hibernate_session freezes a mid-decode session whole into the cold
    arena (waiter sees SessionHibernated, device pages freed);
    restore_hibernated resumes it token-identically and the stream dedupes
    to an exactly-once sequence across the gap."""
    be = ArenaFakeBackend(num_pages=32, page_size=4, max_context=128,
                          step_delay=0.01)
    eng = ServingEngine(be, run_blocking=run_blocking, max_new_tokens_cap=64)
    tap = Tap()
    prompt = [3, 1, 4, 1, 5]
    src = asyncio.ensure_future(eng.submit(
        GenRequest(prompt=prompt, max_new_tokens=24, stream=True,
                   session_key="hib"),
        job_id="h1", on_tokens=tap))
    await wait_until(
        lambda: (eng.export_state("h1") or {}).get("pos", 0) >= 10,
        msg="session mid-decode")
    assert await eng.hibernate_session("h1") is True
    with pytest.raises(SessionHibernated):
        await asyncio.wait_for(src, timeout=5)
    assert eng.allocator.used_pages == 0
    assert "h1" in eng.tiering.arena and eng.tiering.arena.bytes > 0
    assert eng.stats.hibernated_out == 1
    fut = await eng.restore_hibernated("h1", on_tokens=tap)
    toks = await asyncio.wait_for(fut, timeout=20)
    assert toks == arena_ref(prompt, 24)
    assert eng.stats.restored_in == 1
    await wait_until(lambda: len(tap.buf) == 24, msg="stream complete")
    assert tap.buf == toks  # exactly-once across the hibernate gap
    assert len(eng.tiering.arena) == 0 and eng.tiering.arena.bytes == 0
    eng.allocator.check_consistency()
    await eng.stop()


async def test_random_interleaving_accounting_property():
    """Random admissions over shared prompt pools interleaved with
    hibernate sweeps: every session's tokens match the sequential oracle
    and the allocator's invariants hold at every checkpoint."""
    rng = random.Random(99)
    be = ArenaFakeBackend(num_pages=24, page_size=4, max_context=96,
                          step_delay=0.001)
    eng = ServingEngine(be, run_blocking=run_blocking, max_sessions=6,
                        max_new_tokens_cap=64, hibernate_after_s=30.0)
    base = [[rng.randrange(1, 200) for _ in range(rng.randint(4, 10))]
            for _ in range(3)]
    expected: dict[str, list[int]] = {}
    tasks = []
    for i in range(18):
        if rng.random() < 0.6:
            prompt = list(rng.choice(base)) + [
                rng.randrange(1, 200) for _ in range(rng.randint(0, 4))]
        else:
            prompt = [rng.randrange(1, 200) for _ in range(rng.randint(1, 10))]
        n_new = rng.randint(2, 10)
        jid = f"r{i}"
        expected[jid] = arena_ref(prompt, n_new)
        tasks.append(asyncio.ensure_future(eng.submit(
            GenRequest(prompt=prompt, max_new_tokens=n_new, stream=False,
                       session_key=f"conv{i % 5}"),
            job_id=jid)))
        if rng.random() < 0.4:
            await asyncio.sleep(0.005)
            # alternate aggressive and no-op sweeps mid-flight
            shift = 60 if rng.random() < 0.5 else -60
            await eng.tiering.sweep(now=time.monotonic() + shift)
            eng.allocator.check_consistency(live_tables={
                s.job_id: s.pages for s in eng._active.values()})
    outs = await asyncio.wait_for(asyncio.gather(*tasks), timeout=60)
    for jid, out in zip(expected, outs):
        assert out["tokens"] == expected[jid], jid
    eng.allocator.check_consistency(live_tables={
        s.job_id: s.pages for s in eng._active.values()})
    assert eng.stats.prefix_hits > 0  # the pools actually shared
    # drain the cache completely: every page accounted back to the free list
    eng.prefix.evict(eng.allocator.capacity)
    assert eng.allocator.used_pages == 0
    eng.allocator.check_consistency()
    await eng.stop()


# --------------------------------------------------- CI perf-floor wiring


def test_floor_checker_gates_chat_keys():
    import json
    import sys
    from pathlib import Path

    repo = Path(__file__).resolve().parents[1]
    sys.path.insert(0, str(repo / "tools"))
    try:
        import check_bench_floor as mod
    finally:
        sys.path.pop(0)
    floors = json.loads((repo / "bench_floor.json").read_text())
    base = {"chat_prefix_ttft_speedup": 2.4, "chat_token_identical": 1,
            "chat_prefix_hit_rate": 0.857, "chat_resident_over_capacity": 1.6,
            "chat_restored_pages": 8, "chat_restore_pause_p50_ms": 1.0}
    # healthy values: no chat-key violations (other keys flag missing)
    assert not any("chat" in v for v in mod.check(dict(base), floors))
    for key, bad in [("chat_prefix_ttft_speedup", 1.0),
                     ("chat_token_identical", 0),
                     ("chat_prefix_hit_rate", 0.1),
                     ("chat_resident_over_capacity", 0.9),
                     ("chat_restored_pages", 0),
                     ("chat_restore_pause_p50_ms", 900.0)]:
        doc = dict(base)
        doc[key] = bad
        assert any(key in v for v in mod.check(doc, floors)), key
    # a missing chat key is itself a violation (the gate cannot be skipped)
    doc = dict(base)
    doc.pop("chat_token_identical")
    assert any("chat_token_identical" in v for v in mod.check(doc, floors))


# ---------------------------------------------------- real backend (fp32)


async def test_prefix_and_hibernate_real_backend_oracle():
    """On the real paged-Llama backend: a session sharing a cached system
    prefix produces EXACTLY the fp32 sequential-oracle tokens (sharing is a
    placement change, not a math change), and a hibernate → restore cycle
    through host-RAM records is bit-identical to never hibernating."""
    import jax
    import jax.numpy as jnp

    from cordum_tpu.models import llama
    from cordum_tpu.serving.backend import LlamaServingBackend

    from .test_serving import ref_greedy

    cfg = llama.LlamaConfig(vocab_size=256, d_model=64, n_layers=2, n_heads=4,
                            n_kv_heads=2, d_ff=128, max_seq_len=128,
                            dtype=jnp.float32)
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    be = LlamaServingBackend(cfg, num_pages=64, page_size=8,
                             params_provider=lambda: params)
    eng = ServingEngine(be, run_blocking=run_blocking, max_new_tokens_cap=64,
                        hibernate_after_s=30.0)
    assert eng.prefix is not None  # the real backend carries copy_page
    system = [7, 3, 11, 19, 2, 5, 23, 1]  # exactly one 8-slot page
    p1 = system + [13, 4]
    out1 = await asyncio.wait_for(eng.submit(
        GenRequest(prompt=p1, max_new_tokens=8, stream=False,
                   session_key="s1"),
        job_id="rb1"), timeout=180)
    assert out1["tokens"] == ref_greedy(cfg, params, p1, 8)
    p2 = system + [42, 9, 77]
    out2 = await asyncio.wait_for(eng.submit(
        GenRequest(prompt=p2, max_new_tokens=8, stream=False,
                   session_key="s2"),
        job_id="rb2"), timeout=180)
    assert eng.stats.prefix_hits >= 1 and eng.stats.prefix_hit_tokens >= 8
    assert out2["tokens"] == ref_greedy(cfg, params, p2, 8)
    # hibernate every idle cached page, then a third turn restores them
    demoted = await eng.tiering.sweep(now=time.monotonic() + 60)
    assert demoted >= 1 and eng.prefix.warm_pages == 0
    p3 = p1 + out1["tokens"][:2]
    out3 = await asyncio.wait_for(eng.submit(
        GenRequest(prompt=p3, max_new_tokens=6, stream=False,
                   session_key="s1"),
        job_id="rb3"), timeout=180)
    assert out3["tokens"] == ref_greedy(cfg, params, p3, 6)
    assert eng.prefix.stats.restored_pages >= 1
    eng.allocator.check_consistency()
    await eng.stop()
