"""Wire contract round-trips, state machine legality, job hashing."""
import pytest

from cordum_tpu.protocol.jobhash import job_hash
from cordum_tpu.protocol.types import (
    ALLOWED_TRANSITIONS,
    BusPacket,
    Constraints,
    Heartbeat,
    JobMetadata,
    JobRequest,
    JobResult,
    JobState,
    PolicyCheckResponse,
    Remediation,
    TERMINAL_STATES,
    is_allowed_transition,
)
from cordum_tpu.utils.globmatch import glob_match, subject_match


def test_packet_roundtrip():
    req = JobRequest(
        job_id="j1",
        topic="job.tpu.matmul",
        tenant_id="t1",
        labels={"a": "b"},
        metadata=JobMetadata(capability="tpu", requires=["tpu", "chips:4"]),
    )
    pkt = BusPacket.wrap(req, sender_id="gw")
    decoded = BusPacket.from_wire(pkt.to_wire())
    assert decoded.kind == "job_request"
    assert decoded.job_request.job_id == "j1"
    assert decoded.job_request.metadata.requires == ["tpu", "chips:4"]
    assert decoded.trace_id == pkt.trace_id
    assert decoded.protocol_version == 1


def test_heartbeat_tpu_fields_roundtrip():
    hb = Heartbeat(
        worker_id="w1", chip_count=8, slice_topology="2x2x2", tpu_duty_cycle=42.5,
        capabilities=["tpu"], pool="tpu-default",
    )
    d = BusPacket.from_wire(BusPacket.wrap(hb).to_wire()).heartbeat
    assert d.chip_count == 8 and d.slice_topology == "2x2x2"
    assert d.tpu_duty_cycle == pytest.approx(42.5)


def test_policy_response_roundtrip():
    resp = PolicyCheckResponse(
        decision="ALLOW_WITH_CONSTRAINTS",
        constraints=Constraints(max_chips=4, allowed_topologies=["2x2x1"]),
        remediations=[Remediation(id="r1", replacement_topic="job.safe")],
    )
    d = PolicyCheckResponse.from_wire(resp.to_wire())
    assert d.constraints.max_chips == 4
    assert d.remediations[0].replacement_topic == "job.safe"


def test_transition_table():
    assert is_allowed_transition("", JobState.PENDING)
    assert is_allowed_transition(JobState.PENDING, JobState.SCHEDULED)
    assert is_allowed_transition(JobState.APPROVAL_REQUIRED, JobState.PENDING)
    assert not is_allowed_transition(JobState.SUCCEEDED, JobState.RUNNING)
    assert not is_allowed_transition(JobState.RUNNING, JobState.PENDING)
    for terminal in TERMINAL_STATES:
        assert not ALLOWED_TRANSITIONS[terminal]


def test_job_hash_excludes_approval_labels():
    req = JobRequest(job_id="j", topic="t", labels={"x": "1"})
    h1 = job_hash(req)
    req2 = JobRequest(job_id="j", topic="t", labels={"x": "1", "approval_granted": "true"})
    assert job_hash(req2) == h1
    req3 = JobRequest(job_id="j", topic="t", labels={"x": "2"})
    assert job_hash(req3) != h1
    req4 = JobRequest(job_id="j", topic="t", labels={"x": "1"}, env={"CORDUM_EFFECTIVE_CONFIG": "{}"})
    assert job_hash(req4) == h1


def test_subject_match():
    assert subject_match("job.*", "job.default")
    assert not subject_match("job.*", "job.a.b")
    assert subject_match("sys.job.>", "sys.job.submit")
    assert subject_match("worker.*.jobs", "worker.w1.jobs")
    assert not subject_match("worker.*.jobs", "worker.w1.other")


def test_glob_match():
    assert glob_match("job.*", "job.echo")
    assert not glob_match("job.*", "job.a.b")
    assert glob_match("job.>", "job.a.b")
    assert glob_match("deploy-*", "deploy-prod")
    assert glob_match("*", "anything.at.all")


def test_pruned_wire_fields_tolerate_legacy_peers():
    """CL010 prunes (parent_job_id, artifact_ptrs, sender, approval_ref,
    max_output_tokens) must stay read-compatible: a packet from an old peer
    that still encodes them decodes cleanly, and what we emit round-trips."""
    legacy = {
        "job_id": "j-legacy",
        "topic": "llm.generate",
        "parent_job_id": "j-parent",  # pruned field, still on old wires
        "labels": {"k": "v"},
        "context_hints": {"max_input_tokens": 8, "max_output_tokens": 9,
                          "mode": "CHAT"},
    }
    req = JobRequest.from_dict(legacy)
    assert req.job_id == "j-legacy"
    assert req.context_hints is not None
    assert req.context_hints.max_input_tokens == 8
    assert not hasattr(req, "parent_job_id")
    assert not hasattr(req.context_hints, "max_output_tokens")
    # what we emit round-trips through the wire codec unchanged
    again = JobRequest.from_wire(req.to_wire())
    assert again == req

    resp = PolicyCheckResponse.from_dict({
        "decision": "require_approval",
        "approval_required": True,
        "approval_ref": "tick-123",  # pruned
    })
    assert resp.approval_required is True
    assert not hasattr(resp, "approval_ref")
    assert PolicyCheckResponse.from_wire(resp.to_wire()) == resp
