"""Statebus replication invariants (ISSUE 8, docs/PROTOCOL.md §Replication):

* replica byte-for-byte KV equivalence after random op streams (incremental
  AND snapshot attach paths),
* sync-ack mode survives a primary kill with zero acked-commit loss,
* async mode bounds loss to the unacked replication window,
* promotion is exclusive (epoch fencing: a returning old primary demotes
  itself — no split-brain dual-accept),
* client failover: replica-set walk, resubscription, in-flight retransmit,
  reconnect metrics,
* AOF tail-corruption recovery (fuzz over random truncation points).
"""
from __future__ import annotations

import asyncio
import collections
import json
import os
import random
import subprocess
import sys
import time
from pathlib import Path

import msgpack
import pytest

from cordum_tpu.infra.chaos import ChaosProxy
from cordum_tpu.infra.kv import MemoryKV
from cordum_tpu.infra.metrics import Metrics
from cordum_tpu.infra.replication import parse_endpoint, parse_replica_set, probe_role
from cordum_tpu.infra.statebus import StateBusServer, StateBusConn, connect
from cordum_tpu.protocol import subjects as subj
from cordum_tpu.protocol.types import BusPacket, JobRequest


async def start_server(**kw) -> StateBusServer:
    srv = StateBusServer(port=0, **kw)
    await srv.start()
    return srv


async def start_replica(primary: StateBusServer, **kw) -> StateBusServer:
    return await start_server(
        replica_of=f"statebus://127.0.0.1:{primary.port}", **kw)


async def wait_for(cond, timeout_s: float = 10.0, msg: str = "condition"):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        v = cond()
        if asyncio.iscoroutine(v):
            v = await v
        if v:
            return
        await asyncio.sleep(0.01)
    raise AssertionError(f"timed out waiting for {msg}")


async def wait_caught_up(primary: StateBusServer, replica: StateBusServer,
                         timeout_s: float = 10.0) -> None:
    await wait_for(lambda: replica.repl.offset >= primary.repl.offset,
                   timeout_s, "replica catch-up")


def _rand_ops(rng: random.Random, n: int):
    """A reproducible random mutation stream over a small keyspace."""
    ops = []
    for i in range(n):
        k = f"k{rng.randrange(12)}"
        ops.append(rng.choice([
            ("set", k, f"v{i}".encode()),
            ("hset", f"h{rng.randrange(4)}", {f"f{rng.randrange(3)}": str(i).encode()}),
            ("zadd", f"z{rng.randrange(3)}", f"m{rng.randrange(6)}", float(i)),
            ("rpush", f"l{rng.randrange(3)}", str(i).encode()),
            ("sadd", f"s{rng.randrange(3)}", f"m{rng.randrange(6)}"),
            ("delete", k),
        ]))
    return ops


async def _apply_ops(kv, ops) -> None:
    for name, *args in ops:
        await getattr(kv, name)(*args)


def test_parse_replica_set():
    assert parse_endpoint("statebus://h:7520") == ("h", 7520)
    assert parse_endpoint("h:7520") == ("h", 7520)
    assert parse_replica_set(
        "statebus://a:7420|statebus://b:7520") == [("a", 7420), ("b", 7520)]
    assert parse_replica_set("statebus://a:7420") == [("a", 7420)]


async def test_replica_mirrors_random_op_stream():
    """Byte-for-byte equivalence: a replica attached from genesis mirrors a
    random op stream exactly — snapshots (values AND versions) identical."""
    primary = await start_server()
    replica = await start_replica(primary)
    kv, _, conn = await connect(f"statebus://127.0.0.1:{primary.port}")
    try:
        await wait_for(lambda: primary.repl.sessions, msg="replica attach")
        await _apply_ops(kv, _rand_ops(random.Random(8), 300))
        # pipes replicate as one atomic record
        ok, _ = await kv.pipe_execute({}, [("set", "pk", b"pv"),
                                           ("hset", "ph", {"f": b"1"})])
        assert ok
        await wait_caught_up(primary, replica)
        assert await primary.kv.snapshot() == await replica.kv.snapshot()
        assert replica.repl.epoch == primary.repl.epoch
    finally:
        await conn.close()
        await replica.stop()
        await primary.stop()


async def test_late_replica_reseeds_via_snapshot():
    """A replica too far behind the record backlog is re-seeded with a full
    snapshot — and still ends byte-for-byte identical."""
    primary = await start_server()
    primary.repl.backlog = collections.deque(maxlen=4)  # force snapshot path
    kv, _, conn = await connect(f"statebus://127.0.0.1:{primary.port}")
    replica = None
    try:
        await _apply_ops(kv, _rand_ops(random.Random(9), 120))
        replica = await start_replica(primary)
        await wait_for(lambda: replica._replica_link is not None
                       and replica._replica_link.connected.is_set(),
                       msg="replica link")
        assert replica._replica_link.last_sync_mode == "snapshot"
        await wait_caught_up(primary, replica)
        # post-snapshot stream continues incrementally
        await kv.set("after-snap", b"yes")
        await wait_caught_up(primary, replica)
        assert await primary.kv.snapshot() == await replica.kv.snapshot()
    finally:
        await conn.close()
        if replica is not None:
            await replica.stop()
        await primary.stop()


async def test_snapshot_preserves_versions():
    """Snapshot transfer keeps per-key versions, so watches held by clients
    that fail over to a freshly seeded replica stay valid."""
    src = MemoryKV()
    await src.set("a", b"1")
    await src.set("a", b"2")
    await src.set("a", b"3")
    await src.hset("h", {"f": b"x"})
    ver = await src.version("a")
    dst = MemoryKV()
    await dst.load_snapshot(await src.snapshot())
    assert await dst.get("a") == b"3"
    assert await dst.version("a") == ver
    assert await dst.commit({"a": ver}, [("set", "a", b"4")]) is True


async def test_replica_rejects_writes():
    primary = await start_server()
    replica = await start_replica(primary)
    kv, _, conn = await connect(f"statebus://127.0.0.1:{replica.port}")
    try:
        assert await kv.get("nope") is None  # reads serve
        with pytest.raises(RuntimeError, match="READONLY"):
            await kv.set("nope", b"1")
        with pytest.raises(RuntimeError, match="READONLY"):
            await kv.pipe_execute({}, [("set", "nope", b"1")])
    finally:
        await conn.close()
        await replica.stop()
        await primary.stop()


@pytest.mark.statebus
async def test_sync_mode_zero_acked_commit_loss_on_primary_crash():
    """The headline sync-ack invariant: every write the client saw `ok` for
    survives a primary SIGKILL-style crash and replica promotion."""
    primary = await start_server(sync_replication=True,
                                 heartbeat_interval_s=0.1,
                                 heartbeat_timeout_s=0.5)
    replica = await start_replica(primary, heartbeat_interval_s=0.1,
                                  heartbeat_timeout_s=0.5)
    url = (f"statebus://127.0.0.1:{primary.port}"
           f"|statebus://127.0.0.1:{replica.port}")
    kv, _, conn = await connect(url)
    acked: list[int] = []
    try:
        await wait_for(lambda: primary.repl.sessions, msg="replica attach")

        async def writer(i: int) -> None:
            await kv.set(f"sync-{i}", str(i).encode(), )
            acked.append(i)

        # concurrent burst; crash the primary mid-stream
        tasks = [asyncio.ensure_future(writer(i)) for i in range(60)]
        await wait_for(lambda: len(acked) >= 10, msg="some acks")
        await primary.crash()
        # the failover walk retries the parked writes on the promoted
        # replica, so every writer eventually completes
        await asyncio.gather(*tasks)
        assert replica.role == "primary"
        for i in acked:
            assert await replica.kv.get(f"sync-{i}") == str(i).encode(), (
                f"acked commit sync-{i} lost across failover")
    finally:
        await conn.close()
        await replica.stop()
        await primary.stop()


async def test_async_mode_loss_bounded_to_unacked_window():
    """Async mode: a black-holed replication link bounds loss to EXACTLY the
    records committed after the link went dark — nothing before is lost,
    nothing after the promotion is half-applied."""
    primary = await start_server()
    proxy = ChaosProxy("127.0.0.1", primary.port)
    await proxy.start()
    replica = await start_server(
        replica_of=f"statebus://{proxy.listen_host}:{proxy.port}",
        heartbeat_interval_s=0.1, heartbeat_timeout_s=0.6)
    kv, _, conn = await connect(f"statebus://127.0.0.1:{primary.port}")
    try:
        await wait_for(lambda: primary.repl.sessions, msg="replica attach")
        for i in range(20):
            await kv.set(f"a-{i}", b"x")
        await wait_caught_up(primary, replica)
        replicated_offset = replica.repl.offset
        proxy.blackhole()
        for i in range(15):
            await kv.set(f"b-{i}", b"y")  # acked async; never replicated
        await primary.crash()
        await wait_for(lambda: replica.role == "primary", 5.0, "auto-promote")
        assert replica.repl.offset == replicated_offset
        for i in range(20):
            assert await replica.kv.get(f"a-{i}") == b"x"
        for i in range(15):
            assert await replica.kv.get(f"b-{i}") is None
    finally:
        await conn.close()
        await proxy.stop()
        await replica.stop()
        await primary.stop()


async def test_goaway_promotes_replica_immediately():
    """Graceful primary shutdown (SIGTERM path) broadcasts GOAWAY: the
    replica promotes NOW instead of waiting out the heartbeat timeout."""
    primary = await start_server(heartbeat_timeout_s=30.0)
    replica = await start_replica(primary, heartbeat_timeout_s=30.0)
    try:
        await wait_for(lambda: primary.repl.sessions, msg="replica attach")
        t0 = time.monotonic()
        await primary.stop()  # graceful: GOAWAY broadcast
        await wait_for(lambda: replica.role == "primary", 5.0, "goaway promote")
        assert time.monotonic() - t0 < 5.0  # nowhere near the 30s heartbeat
        text = replica.metrics.render()
        assert 'reason="primary-goaway"' in text
    finally:
        await replica.stop()
        await primary.stop()


async def test_admin_promote_and_role_frames():
    primary = await start_server()
    replica = await start_replica(primary)
    kv, _, conn = await connect(f"statebus://127.0.0.1:{replica.port}")
    try:
        await wait_for(lambda: primary.repl.sessions, msg="replica attach")
        doc = await probe_role("127.0.0.1", primary.port)
        assert doc["role"] == "primary" and doc["replicas"]
        doc = await conn.call("role")
        assert doc["role"] == "replica"
        doc = await conn.call("promote")
        assert doc["role"] == "primary" and doc["epoch"] == 1
        await kv.set("now-writable", b"1")  # writes accepted post-promotion
        assert await kv.get("now-writable") == b"1"
    finally:
        await conn.close()
        await replica.stop()
        await primary.stop()


@pytest.mark.statebus
async def test_promotion_is_exclusive_old_primary_demotes():
    """Epoch fencing: a promoted replica bumps + persists its epoch; the old
    primary returning finds a live higher-epoch primary in its peer set,
    demotes itself to replica, and re-syncs — no dual-accept."""
    primary = await start_server(heartbeat_interval_s=0.1,
                                 heartbeat_timeout_s=0.5)
    replica = await start_replica(primary, heartbeat_interval_s=0.1,
                                  heartbeat_timeout_s=0.5)
    kv, _, conn = await connect(f"statebus://127.0.0.1:{primary.port}")
    old_port = primary.port
    try:
        await wait_for(lambda: primary.repl.sessions, msg="replica attach")
        await kv.set("pre-crash", b"1")
        await wait_caught_up(primary, replica)
        await conn.close()
        await primary.crash()
        await wait_for(lambda: replica.role == "primary", 5.0, "auto-promote")
        assert replica.repl.epoch == 1
        # old primary returns on its old port, with the replica in its peer
        # set: the startup probe finds the higher epoch and demotes it
        returned = StateBusServer(
            port=old_port,
            peers=(f"statebus://127.0.0.1:{old_port}",
                   f"statebus://127.0.0.1:{replica.port}"))
        await returned.start()
        await wait_for(lambda: returned.role == "replica", 5.0, "self-demotion")
        assert returned.replica_of.endswith(str(replica.port))
        # exactly one writable node: the returned server rejects writes...
        kv2, _, conn2 = await connect(f"statebus://127.0.0.1:{old_port}")
        with pytest.raises(RuntimeError, match="READONLY"):
            await kv2.set("split-brain", b"!")
        await conn2.close()
        # ...and mirrors the new primary's stream
        kv3, _, conn3 = await connect(f"statebus://127.0.0.1:{replica.port}")
        await kv3.set("post-promotion", b"2")
        await wait_caught_up(replica, returned)
        assert await returned.kv.get("post-promotion") == b"2"
        assert await returned.kv.get("pre-crash") == b"1"
        assert returned.repl.epoch == replica.repl.epoch
        await conn3.close()
        await returned.stop()
    finally:
        await replica.stop()
        await primary.stop()


async def test_client_failover_resubscribes_and_counts_reconnects():
    """StateBusConn walks the replica set on primary loss, re-issues every
    subscription, and counts the failover in
    cordum_statebus_reconnects_total{reason}."""
    primary = await start_server(heartbeat_interval_s=0.1,
                                 heartbeat_timeout_s=0.4)
    replica = await start_replica(primary, heartbeat_interval_s=0.1,
                                  heartbeat_timeout_s=0.4)
    url = (f"statebus://127.0.0.1:{primary.port}"
           f"|statebus://127.0.0.1:{replica.port}")
    kv, bus, conn = await connect(url)
    m = Metrics()
    kv.bind_metrics(m)
    got: list[str] = []
    try:
        async def h(s, p):
            got.append(p.job_request.job_id)

        await bus.subscribe("sys.job.submit", h, queue="g")
        await bus.publish(subj.SUBMIT,
                          BusPacket.wrap(JobRequest(job_id="before", topic="t")))
        await wait_for(lambda: got == ["before"], msg="pre-failover delivery")
        await primary.crash()
        await wait_for(lambda: replica.role == "primary", 5.0, "auto-promote")
        await bus.publish(subj.SUBMIT,
                          BusPacket.wrap(JobRequest(job_id="after", topic="t")))
        await wait_for(lambda: got == ["before", "after"], 10.0,
                       "post-failover delivery via re-issued subscription")
        assert conn.reconnect_count >= 1
        assert m.statebus_reconnects.total() >= 1
        assert (conn.host, conn.port) == ("127.0.0.1", replica.port)
    finally:
        await conn.close()
        await replica.stop()
        await primary.stop()


async def test_parked_call_retransmits_across_server_restart():
    """A call issued while the server is down parks its frame and completes
    after reconnect — pipelined commits are never silently dropped."""
    from cordum_tpu.infra.chaos import free_port

    port = free_port()
    srv = StateBusServer(port=port)
    await srv.start()
    kv, _, conn = await connect(f"statebus://127.0.0.1:{port}")
    try:
        await kv.set("warm", b"1")
        await srv.crash()
        task = asyncio.ensure_future(kv.set("parked", b"2"))
        await asyncio.sleep(0.1)
        assert not task.done()
        srv2 = StateBusServer(port=port)
        await srv2.start()
        await asyncio.wait_for(task, 10)
        assert await kv.get("parked") == b"2"
        await srv2.stop()
    finally:
        await conn.close()
        await srv.stop()


async def test_sync_ack_timeout_degrades_not_blocks():
    """A replica that stops acking degrades sync→async after the sync
    timeout (counted) instead of holding the partition hostage."""
    primary = await start_server(sync_replication=True)
    primary.repl.sync_timeout_s = 0.3
    proxy = ChaosProxy("127.0.0.1", primary.port)
    await proxy.start()
    replica = await start_server(
        replica_of=f"statebus://{proxy.listen_host}:{proxy.port}",
        heartbeat_timeout_s=30.0, auto_promote=False)
    kv, _, conn = await connect(f"statebus://127.0.0.1:{primary.port}")
    try:
        await wait_for(lambda: primary.repl.sessions, msg="replica attach")
        await kv.set("synced", b"1")  # replica live: fast ack
        proxy.blackhole()
        t0 = time.monotonic()
        await kv.set("degraded", b"2")
        assert time.monotonic() - t0 >= 0.25
        assert primary.metrics.statebus_sync_ack_timeouts.total() == 1
        assert await kv.get("degraded") == b"2"
    finally:
        await conn.close()
        await proxy.stop()
        await replica.stop()
        await primary.stop()


async def test_spuriously_failed_over_primary_demotes_at_runtime():
    """The OTHER split-brain direction: a primary that never died but whose
    replica promoted anyway (a stall read as primary-dead) finds the
    higher-epoch primary at its next peer probe and demotes itself —
    WITHOUT a restart, so dual-accept is bounded by the probe interval."""
    primary = await start_server(heartbeat_interval_s=0.05,
                                 heartbeat_timeout_s=0.2)
    replica = await start_replica(primary, heartbeat_interval_s=0.05,
                                  heartbeat_timeout_s=30.0)
    primary.peers = (f"statebus://127.0.0.1:{primary.port}",
                     f"statebus://127.0.0.1:{replica.port}")
    kv, _, conn = await connect(f"statebus://127.0.0.1:{primary.port}")
    try:
        await wait_for(lambda: primary.repl.sessions, msg="replica attach")
        await kv.set("pre-split", b"1")
        await wait_caught_up(primary, replica)
        # spurious promotion: the replica is promoted while the primary is
        # alive and healthy — two primaries exist for a moment
        await replica.promote(reason="admin")
        assert primary.role == "primary" and replica.role == "primary"
        await wait_for(lambda: primary.role == "replica", 10.0,
                       "runtime self-demotion")
        # epoch adoption rides the re-sync handshake, just after the flip
        await wait_for(lambda: primary.repl.epoch == 1, 10.0, "epoch adoption")
        assert replica.repl.epoch == 1
        # exactly one writable node again, and the demoted server mirrors it
        kv2, _, conn2 = await connect(f"statebus://127.0.0.1:{replica.port}")
        await kv2.set("post-split", b"2")
        await wait_caught_up(replica, primary)
        assert await primary.kv.get("post-split") == b"2"
        await conn2.close()
    finally:
        await conn.close()
        await replica.stop()
        await primary.stop()


@pytest.mark.statebus
async def test_cli_statebus_status_and_promote():
    """`cordumctl statebus status` renders per-partition role/offset/lag
    straight from the fleet; `statebus promote` drives the admin frame."""
    primary = await start_server()
    replica = await start_replica(primary)
    url = (f"statebus://127.0.0.1:{primary.port}"
           f"|statebus://127.0.0.1:{replica.port}")

    def run_cli(*args: str) -> subprocess.CompletedProcess:
        return subprocess.run(
            [sys.executable, "-m", "cordum_tpu.cli", *args],
            capture_output=True, text=True, timeout=60,
            cwd=str(Path(__file__).resolve().parents[1]),
            env={**os.environ, "JAX_PLATFORMS": "cpu"})

    try:
        await wait_for(lambda: primary.repl.sessions, msg="replica attach")
        out = await asyncio.to_thread(run_cli, "statebus", "status",
                                      "--url", url, "--json")
        assert out.returncode == 0, out.stderr
        rows = json.loads(out.stdout)
        assert [r["role"] for r in rows] == ["primary", "replica"]
        assert rows[0]["replicas"] == 1 and rows[0]["partition"] == 0
        out = await asyncio.to_thread(
            run_cli, "statebus", "promote",
            f"statebus://127.0.0.1:{replica.port}")
        assert out.returncode == 0, out.stderr
        doc = json.loads(out.stdout)
        assert doc["role"] == "primary" and doc["epoch"] == 1
        assert replica.role == "primary"
        # the table renderer also holds together (no --json)
        out = await asyncio.to_thread(run_cli, "statebus", "status", "--url", url)
        assert out.returncode == 0 and "endpoint" in out.stdout
    finally:
        await replica.stop()
        await primary.stop()


# ---------------------------------------------------------------------------
# AOF tail-corruption recovery (crash mid-write)
# ---------------------------------------------------------------------------


async def _complete_prefix_state(blob: bytes) -> tuple[int, dict]:
    """Oracle: apply every COMPLETE well-formed record in `blob` to a fresh
    MemoryKV (mirroring replay semantics) and return (n_records, k→v)."""
    unpacker = msgpack.Unpacker(raw=False, strict_map_key=False)
    unpacker.feed(blob)
    kv = MemoryKV()
    n = 0
    while True:
        try:
            entry = unpacker.unpack()
        except msgpack.OutOfData:
            break
        except Exception:  # noqa: BLE001 - garbage tail is the point
            break
        if (not isinstance(entry, (list, tuple)) or not entry
                or not isinstance(entry[0], str)):
            break
        op, args = entry[0], entry[1:]
        if op == "pipe_execute":
            await kv.pipe_execute(*args)
        elif op not in ("repl_meta", "repl_snapshot"):
            await getattr(kv, op)(*args)
        n += 1
    out = {}
    for k in await kv.keys():
        out[k] = await kv.get(k)
    return n, out


@pytest.mark.statebus
async def test_aof_tail_corruption_fuzz(tmp_path):
    """Replay of an AOF truncated at ANY byte (or with a garbage tail)
    recovers to the last complete record — never raises, and appends
    continue from a clean tail afterwards."""
    aof = str(tmp_path / "full.aof")
    srv = await start_server(aof_path=aof)
    kv, _, conn = await connect(f"statebus://127.0.0.1:{srv.port}")
    for i in range(50):
        await kv.set(f"fz-{i}", str(i).encode())
    ok, _ = await kv.pipe_execute({}, [("set", "fz-pipe", b"p"),
                                       ("zadd", "fz-z", "m", 1.0)])
    assert ok
    await conn.close()
    await srv.stop()
    blob = await asyncio.to_thread(_read, aof)
    rng = random.Random(17)
    cuts = sorted(rng.randrange(1, len(blob)) for _ in range(8))
    for case, cut in enumerate([*cuts, None]):  # None = garbage-append case
        path = str(tmp_path / f"cut-{case}.aof")
        data = blob[:cut] if cut is not None else blob + b"\xc1\x00garbage"
        await asyncio.to_thread(_write, path, data)
        expect_n, expect_state = await _complete_prefix_state(data)
        srv2 = await start_server(aof_path=path)
        try:
            got = {k: await srv2.kv.get(k) for k in await srv2.kv.keys()}
            assert got == expect_state, f"cut at {cut}: state diverged"
            assert srv2.repl.offset == expect_n
            # the tail was truncated clean: appends + another replay work
            kv2, _, conn2 = await connect(f"statebus://127.0.0.1:{srv2.port}")
            await kv2.set("post-recovery", b"ok")
            await conn2.close()
        finally:
            await srv2.stop()
        srv3 = await start_server(aof_path=path)
        try:
            assert await srv3.kv.get("post-recovery") == b"ok"
        finally:
            await srv3.stop()


def _read(path: str) -> bytes:
    with open(path, "rb") as f:
        return f.read()


def _write(path: str, data: bytes) -> None:
    with open(path, "wb") as f:
        f.write(data)
