"""Safety kernel: rule matching, first-match-wins, MCP gates, legacy tenant
fallback, snapshots, decision cache, circuit breaker fail-closed."""
import asyncio

import pytest

from cordum_tpu.controlplane.safetykernel.kernel import SafetyKernel
from cordum_tpu.controlplane.safetykernel.policy import SafetyPolicy, evaluate
from cordum_tpu.controlplane.scheduler.safety_client import CircuitBreaker, SafetyClient
from cordum_tpu.infra.configsvc import ConfigService
from cordum_tpu.protocol.types import JobMetadata, PolicyCheckRequest

POLICY_YAML = """
default_tenant: default
tenants:
  default:
    allow_topics: ["job.*", "job.>"]
    deny_topics: ["sys.*"]
    mcp:
      deny_servers: ["evil-*"]
      allow_tools: ["search", "read_*"]
rules:
  - id: deny-prod-deploy
    match:
      topics: ["job.deploy.*"]
      risk_tags: ["prod"]
    decision: deny
    reason: "prod deploys are blocked"
  - id: approve-tpu-big
    match:
      capabilities: ["tpu"]
      requires: ["chips:8"]
    decision: require_approval
    reason: "full-slice jobs need approval"
  - id: constrain-tpu
    match:
      capabilities: ["tpu"]
    decision: allow_with_constraints
    constraints:
      max_chips: 4
      max_tokens: 1000
      allowed_topologies: ["2x2x1"]
  - id: throttle-batch
    match:
      labels: {"class": "bulk"}
    decision: throttle
    throttle_delay_s: 2.5
"""


def _policy():
    return SafetyPolicy.from_yaml(POLICY_YAML)


def test_first_match_wins_and_deny():
    pol = _policy()
    resp = evaluate(
        pol,
        PolicyCheckRequest(
            topic="job.deploy.api",
            metadata=JobMetadata(capability="tpu", risk_tags=["prod"], requires=["chips:8"]),
        ),
    )
    assert resp.decision == "DENY"
    assert resp.rule_id == "deny-prod-deploy"


def test_require_approval_and_constraints():
    pol = _policy()
    resp = evaluate(
        pol,
        PolicyCheckRequest(topic="job.x", metadata=JobMetadata(capability="tpu", requires=["chips:8", "tpu"])),
    )
    assert resp.decision == "REQUIRE_APPROVAL" and resp.approval_required
    resp2 = evaluate(
        pol, PolicyCheckRequest(topic="job.x", metadata=JobMetadata(capability="tpu"))
    )
    assert resp2.decision == "ALLOW_WITH_CONSTRAINTS"
    assert resp2.constraints.max_chips == 4
    assert resp2.constraints.allowed_topologies == ["2x2x1"]


def test_throttle_and_label_match():
    resp = evaluate(_policy(), PolicyCheckRequest(topic="job.x", labels={"class": "bulk"}))
    assert resp.decision == "THROTTLE"
    assert resp.throttle_delay_s == pytest.approx(2.5)


def test_legacy_tenant_fallback():
    pol = _policy()
    assert evaluate(pol, PolicyCheckRequest(topic="job.echo")).decision == "ALLOW"
    assert evaluate(pol, PolicyCheckRequest(topic="sys.hack")).decision == "DENY"
    assert evaluate(pol, PolicyCheckRequest(topic="other.thing")).decision == "DENY"


def test_mcp_gates():
    pol = _policy()
    r = evaluate(pol, PolicyCheckRequest(topic="job.x", labels={"mcp.server": "evil-corp"}))
    assert r.decision == "DENY" and "mcp" in r.reason
    r2 = evaluate(pol, PolicyCheckRequest(topic="job.x", labels={"mcp.server": "ok", "mcp.tool": "read_file"}))
    assert r2.decision == "ALLOW"
    r3 = evaluate(pol, PolicyCheckRequest(topic="job.x", labels={"mcp.tool": "delete_everything"}))
    assert r3.decision == "DENY"


async def test_kernel_snapshots_and_fragments(kv):
    import yaml

    cs = ConfigService(kv)
    kernel = SafetyKernel(policy_doc=yaml.safe_load(POLICY_YAML), configsvc=cs)
    snap1 = await kernel.reload()
    assert ":" in snap1
    # adding an enabled policy fragment changes the snapshot
    await cs.set(
        "system",
        "policy/extra-deny",
        {"enabled": True, "rules": [{"id": "frag", "match": {"topics": ["job.frag"]}, "decision": "deny"}]},
    )
    snap2 = await kernel.reload()
    assert snap2 != snap1
    resp = await kernel.check(PolicyCheckRequest(topic="job.frag"))
    assert resp.decision == "DENY" and resp.rule_id == "frag"
    # disabled fragments are ignored
    await cs.set("system", "policy/extra-deny", {"enabled": False, "rules": [{"id": "frag", "decision": "deny"}]})
    await kernel.reload()
    resp = await kernel.check(PolicyCheckRequest(topic="job.frag"))
    assert resp.decision == "ALLOW"
    assert len(kernel.list_snapshots()) == 3
    assert kernel.get_snapshot(snap1) is not None


async def test_kernel_decision_cache(kv):
    import yaml

    kernel = SafetyKernel(policy_doc=yaml.safe_load(POLICY_YAML))
    await kernel.reload()
    r1 = await kernel.check(PolicyCheckRequest(job_id="a", topic="job.x"))
    r2 = await kernel.check(PolicyCheckRequest(job_id="b", topic="job.x"))
    assert r2 is r1  # cache key excludes job_id


async def test_kernel_effective_config_overrides():
    kernel = SafetyKernel(policy_doc={})
    await kernel.reload()
    req = PolicyCheckRequest(topic="job.x", effective_config={"safety": {"denied_topics": ["job.x"]}})
    assert (await kernel.check(req)).decision == "DENY"
    req2 = PolicyCheckRequest(
        topic="job.y", effective_config={"safety": {"allowed_topics": ["job.z"]}}
    )
    assert (await kernel.check(req2)).decision == "DENY"


async def test_kernel_explain_and_simulate():
    import yaml

    kernel = SafetyKernel(policy_doc=yaml.safe_load(POLICY_YAML))
    await kernel.reload()
    exp = await kernel.explain(PolicyCheckRequest(topic="job.x", labels={"class": "bulk"}))
    assert exp["decision"]["decision"] == "THROTTLE"
    assert any(t["matched"] for t in exp["trail"])
    sims = await kernel.simulate(
        {"rules": [{"id": "d", "match": {"topics": ["job.*"]}, "decision": "deny"}]},
        [PolicyCheckRequest(topic="job.x")],
    )
    assert sims[0]["decision"] == "DENY"


# ---------------------------------------------------------------- client

async def test_safety_client_fail_closed_and_breaker():
    calls = []

    async def failing(req):
        calls.append(1)
        raise RuntimeError("kernel down")

    breaker = CircuitBreaker(fail_threshold=3, open_seconds=9999)
    client = SafetyClient(failing, timeout_s=0.1, breaker=breaker)
    for _ in range(3):
        resp = await client.check(PolicyCheckRequest(topic="job.x"))
        assert resp.decision == "DENY"
    assert breaker.state == CircuitBreaker.OPEN
    # circuit open: denies without calling the kernel
    n = len(calls)
    resp = await client.check(PolicyCheckRequest(topic="job.x"))
    assert resp.decision == "DENY" and len(calls) == n


async def test_safety_client_half_open_recovery():
    ok = {"v": False}

    async def flaky(req):
        if not ok["v"]:
            raise RuntimeError("down")
        from cordum_tpu.protocol.types import PolicyCheckResponse

        return PolicyCheckResponse(decision="ALLOW")

    breaker = CircuitBreaker(fail_threshold=1, open_seconds=0.01, close_successes=2)
    client = SafetyClient(flaky, breaker=breaker)
    await client.check(PolicyCheckRequest(topic="t"))
    assert breaker.state == CircuitBreaker.OPEN
    await asyncio.sleep(0.02)
    ok["v"] = True
    r1 = await client.check(PolicyCheckRequest(topic="t"))
    r2 = await client.check(PolicyCheckRequest(topic="t"))
    assert r1.decision == "ALLOW" and r2.decision == "ALLOW"
    assert breaker.state == CircuitBreaker.CLOSED


async def test_safety_client_timeout_denies():
    async def slow(req):
        await asyncio.sleep(1.0)

    client = SafetyClient(slow, timeout_s=0.01)
    resp = await client.check(PolicyCheckRequest(topic="t"))
    assert resp.decision == "DENY" and "timed out" in resp.reason
