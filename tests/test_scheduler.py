"""Scheduler engine: dispatch flow, safety branches, approval hash binding,
strategy selection, reconciler/replayer loops."""
import asyncio
import time

import pytest

from cordum_tpu.controlplane.scheduler.engine import Engine
from cordum_tpu.controlplane.scheduler.reconciler import PendingReplayer, Reconciler
from cordum_tpu.controlplane.scheduler.safety_client import SafetyClient
from cordum_tpu.controlplane.scheduler.strategy import (
    LeastLoadedStrategy,
    NaiveStrategy,
    is_overloaded,
    load_score,
    worker_satisfies,
)
from cordum_tpu.controlplane.safetykernel.kernel import SafetyKernel
from cordum_tpu.infra.bus import LoopbackBus
from cordum_tpu.infra.config import Pool, PoolConfig, Timeouts, parse_pool_config
from cordum_tpu.infra.configsvc import ConfigService
from cordum_tpu.infra.jobstore import JobStore
from cordum_tpu.infra.kv import MemoryKV
from cordum_tpu.infra.registry import WorkerRegistry
from cordum_tpu.protocol import subjects as subj
from cordum_tpu.protocol.jobhash import job_hash
from cordum_tpu.protocol.types import (
    BusPacket,
    Heartbeat,
    JobMetadata,
    JobRequest,
    JobResult,
    JobState,
)


def make_engine(policy_doc=None, *, pool_doc=None, registry=None, configsvc=None, **kw):
    kv = MemoryKV()
    bus = LoopbackBus(sync=True)
    js = JobStore(kv)
    kernel = SafetyKernel(policy_doc=policy_doc or {})
    client = SafetyClient(kernel.check)
    reg = registry or WorkerRegistry()
    pc = parse_pool_config(
        pool_doc or {"topics": {"job.default": "default"}, "pools": {"default": {}}}
    )
    strat = LeastLoadedStrategy(reg, pc)
    eng = Engine(
        bus=bus, job_store=js, safety=client, strategy=strat, registry=reg,
        configsvc=configsvc, **kw,
    )
    return eng, bus, js, kv, reg


def hb(worker_id, pool="default", **kw):
    return Heartbeat(worker_id=worker_id, pool=pool, max_parallel_jobs=10, **kw)


# ---------------------------------------------------------------- strategy

def test_strategy_least_loaded_picks_lowest_score():
    reg = WorkerRegistry()
    reg.update(hb("w1", active_jobs=5))
    reg.update(hb("w2", active_jobs=1))
    reg.update(hb("w3", active_jobs=1, cpu_load=50))
    strat = LeastLoadedStrategy(reg, parse_pool_config({"topics": {"job.default": "default"}, "pools": {"default": {}}}))
    assert strat.pick_subject(JobRequest(job_id="j", topic="job.default")) == "worker.w2.jobs"


def test_strategy_requires_and_tpu_constraints():
    reg = WorkerRegistry()
    reg.update(hb("cpu1", pool="tpu", capabilities=["echo"]))
    reg.update(hb("tpu1", pool="tpu", capabilities=["tpu"], chip_count=4, slice_topology="2x2x1"))
    reg.update(hb("tpu8", pool="tpu", capabilities=["tpu"], chip_count=8, slice_topology="2x2x2", active_jobs=3))
    pc = parse_pool_config({"topics": {"job.tpu": "tpu"}, "pools": {"tpu": {"requires": ["tpu"]}}})
    strat = LeastLoadedStrategy(reg, pc)
    # chips:8 requirement skips the 4-chip worker
    req = JobRequest(job_id="j", topic="job.tpu", metadata=JobMetadata(requires=["chips:8"]))
    assert strat.pick_subject(req) == "worker.tpu8.jobs"
    # topology requirement
    req2 = JobRequest(job_id="j", topic="job.tpu", metadata=JobMetadata(requires=["topology:2x2x1"]))
    assert strat.pick_subject(req2) == "worker.tpu1.jobs"
    # no eligible worker -> topic fan-in
    req3 = JobRequest(job_id="j", topic="job.tpu", metadata=JobMetadata(requires=["chips:16"]))
    assert strat.pick_subject(req3) == "job.tpu"


def test_strategy_overload_and_health():
    assert is_overloaded(hb("w", active_jobs=9))  # 9 >= 0.9*10
    assert is_overloaded(hb("w", cpu_load=95))
    assert is_overloaded(hb("w", tpu_duty_cycle=95))
    assert is_overloaded(Heartbeat(worker_id="w", devices_healthy=False))
    assert not is_overloaded(hb("w", active_jobs=2))
    assert load_score(hb("w", active_jobs=2, cpu_load=50, tpu_duty_cycle=50)) == pytest.approx(3.0)


def test_strategy_placement_and_hints():
    reg = WorkerRegistry()
    reg.update(hb("w1", labels={"zone": "a"}))
    reg.update(hb("w2", labels={"zone": "b"}, active_jobs=5))
    pc = parse_pool_config({"topics": {"job.default": "default"}, "pools": {"default": {}}})
    strat = LeastLoadedStrategy(reg, pc)
    req = JobRequest(job_id="j", topic="job.default", labels={"placement.zone": "b"})
    assert strat.pick_subject(req) == "worker.w2.jobs"
    req2 = JobRequest(job_id="j", topic="job.default", labels={"preferred_worker_id": "w2"})
    assert strat.pick_subject(req2) == "worker.w2.jobs"


def test_worker_satisfies_device_kind():
    pool = Pool(name="p", device_kind="TPU v5p")
    assert worker_satisfies(Heartbeat(worker_id="w", device_kind="TPU v5p"), pool, [])
    assert not worker_satisfies(Heartbeat(worker_id="w", device_kind="TPU v4"), pool, [])


# ---------------------------------------------------------------- engine

async def test_engine_dispatch_happy_path():
    eng, bus, js, kv, reg = make_engine()
    reg.update(hb("w1"))
    await eng.start()
    req = JobRequest(job_id="j1", topic="job.default", tenant_id="t")
    await bus.publish(subj.SUBMIT, BusPacket.wrap(req, sender_id="test"))
    assert await js.get_state("j1") == "RUNNING"
    meta = await js.get_meta("j1")
    assert meta["dispatch_subject"] == "worker.w1.jobs"
    # dispatched packet reached the worker subject
    dispatched = [s for s, _ in bus.published if s == "worker.w1.jobs"]
    assert dispatched
    # result closes the loop
    res = JobResult(job_id="j1", status="SUCCEEDED", result_ptr="kv://res:j1", worker_id="w1")
    await bus.publish(subj.RESULT, BusPacket.wrap(res, sender_id="w1"))
    assert await js.get_state("j1") == "SUCCEEDED"
    assert (await js.get_meta("j1"))["result_ptr"] == "kv://res:j1"


async def test_engine_deny_goes_to_dlq():
    pol = {"rules": [{"id": "d", "match": {"topics": ["job.bad"]}, "decision": "deny", "reason": "nope"}],
           "tenants": {"default": {"allow_topics": ["job.*"]}}}
    eng, bus, js, kv, reg = make_engine(pol)
    await eng.start()
    await bus.publish(subj.SUBMIT, BusPacket.wrap(JobRequest(job_id="j1", topic="job.bad")))
    assert await js.get_state("j1") == "DENIED"
    dlq = [p for s, p in bus.published if s == subj.DLQ]
    assert dlq and dlq[0].job_result.error_code == "SAFETY_DENY"
    rec = await js.get_safety_decision("j1")
    assert rec.decision == "DENY" and rec.rule_id == "d"


async def test_engine_approval_flow_with_hash_binding():
    pol = {"rules": [{"id": "a", "match": {"topics": ["job.big"]}, "decision": "require_approval"}]}
    eng, bus, js, kv, reg = make_engine(pol)
    reg.update(hb("w1"))
    await eng.start()
    req = JobRequest(job_id="j1", topic="job.big", labels={"x": "1"})
    await bus.publish(subj.SUBMIT, BusPacket.wrap(req))
    assert await js.get_state("j1") == "APPROVAL_REQUIRED"
    rec = await js.get_safety_decision("j1")
    assert rec.job_hash == job_hash(req)

    # tampered republish: hash mismatch → re-check → parks again
    tampered = JobRequest(job_id="j1", topic="job.big", labels={"x": "EVIL", "approval_granted": "true"})
    await eng.handle_job_request(tampered)
    assert await js.get_state("j1") == "APPROVAL_REQUIRED"

    # faithful republish with approval label → dispatched
    approved = JobRequest(job_id="j1", topic="job.big", labels={"x": "1", "approval_granted": "true"})
    await eng.handle_job_request(approved)
    assert await js.get_state("j1") == "RUNNING"


async def test_engine_constraints_applied():
    pol = {"rules": [{"id": "c", "match": {"topics": ["job.tpu"]}, "decision": "allow_with_constraints",
                      "constraints": {"max_chips": 4, "max_tokens": 100, "env": {"SANDBOX": "strict"}}}]}
    eng, bus, js, kv, reg = make_engine(pol, pool_doc={"topics": {"job.tpu": "p"}, "pools": {"p": {}}})
    reg.update(hb("w1", pool="p"))
    await eng.start()
    from cordum_tpu.protocol.types import Budget

    req = JobRequest(job_id="j1", topic="job.tpu", budget=Budget(max_tokens=99999))
    await bus.publish(subj.SUBMIT, BusPacket.wrap(req))
    sent = [p for s, p in bus.published if s == "worker.w1.jobs"][0].job_request
    assert sent.env["CORDUM_MAX_CHIPS"] == "4"
    assert sent.env["SANDBOX"] == "strict"
    assert "CORDUM_POLICY_CONSTRAINTS" in sent.env
    assert sent.budget.max_tokens == 100  # clamped


async def test_engine_effective_config_attached(kv):
    cs = ConfigService(kv)
    await cs.set("system", "default", {"models": {"default_model": "llama-3"}})
    eng, bus, js, _, reg = make_engine(configsvc=cs)
    reg.update(hb("w1"))
    await eng.start()
    await bus.publish(subj.SUBMIT, BusPacket.wrap(JobRequest(job_id="j1", topic="job.default")))
    sent = [p for s, p in bus.published if s == "worker.w1.jobs"][0].job_request
    assert "models" in sent.env["CORDUM_EFFECTIVE_CONFIG"]
    assert (await js.get_meta("j1"))["config_hash"]


async def test_engine_terminal_short_circuit_on_redelivery():
    eng, bus, js, kv, reg = make_engine()
    reg.update(hb("w1"))
    await eng.start()
    req = JobRequest(job_id="j1", topic="job.default")
    await eng.handle_job_request(req)
    await eng.handle_job_result(JobResult(job_id="j1", status="SUCCEEDED"))
    n_published = len(bus.published)
    await eng.handle_job_request(req)  # redelivery after terminal: no-op
    assert len(bus.published) == n_published
    await eng.handle_job_result(JobResult(job_id="j1", status="FAILED"))  # no-op
    assert await js.get_state("j1") == "SUCCEEDED"


async def test_engine_inflight_short_circuit_on_redelivery():
    """A redelivered submit for a RUNNING job must not re-dispatch, re-check
    safety, or burn dispatch attempts toward the DLQ (advisor finding)."""
    eng, bus, js, kv, reg = make_engine()
    reg.update(hb("w1"))
    await eng.start()
    req = JobRequest(job_id="j1", topic="job.default")
    await eng.handle_job_request(req)
    assert await js.get_state("j1") == "RUNNING"
    n_published = len(bus.published)
    attempts = (await js.get_meta("j1"))["attempts"]
    for _ in range(10):  # more duplicates than max_attempts
        await eng.handle_job_request(req)
    assert len(bus.published) == n_published  # nothing re-dispatched
    assert (await js.get_meta("j1"))["attempts"] == attempts
    assert await js.get_state("j1") == "RUNNING"  # not DLQ'd/failed
    await eng.handle_job_result(JobResult(job_id="j1", status="SUCCEEDED"))
    assert await js.get_state("j1") == "SUCCEEDED"


async def test_engine_failed_result_emits_dlq():
    eng, bus, js, kv, reg = make_engine()
    reg.update(hb("w1"))
    await eng.start()
    await eng.handle_job_request(JobRequest(job_id="j1", topic="job.default"))
    await eng.handle_job_result(
        JobResult(job_id="j1", status="FAILED", error_code="BOOM", error_message="exploded")
    )
    dlq = [p for s, p in bus.published if s == subj.DLQ]
    assert dlq and dlq[0].job_result.error_code == "BOOM"


async def test_engine_cancel():
    eng, bus, js, kv, reg = make_engine()
    reg.update(hb("w1"))
    await eng.start()
    await eng.handle_job_request(JobRequest(job_id="j1", topic="job.default"))
    from cordum_tpu.protocol.types import JobCancel

    await bus.publish(subj.CANCEL, BusPacket.wrap(JobCancel(job_id="j1", reason="user")))
    assert await js.get_state("j1") == "CANCELLED"


async def test_engine_tenant_concurrency_limit():
    from cordum_tpu.infra.bus import RetryAfter

    eng, bus, js, kv, reg = make_engine(tenant_concurrency_limit=1)
    reg.update(hb("w1"))
    await eng.handle_job_request(JobRequest(job_id="j1", topic="job.default", tenant_id="t"))
    with pytest.raises(RetryAfter):
        await eng.handle_job_request(JobRequest(job_id="j2", topic="job.default", tenant_id="t"))


async def test_engine_per_tenant_concurrency_from_effective_config(kv):
    """An org-scoped rate_limits.concurrent_jobs bounds that tenant only."""
    from cordum_tpu.infra.bus import RetryAfter

    cs = ConfigService(kv)
    await cs.set("org", "tight", {"rate_limits": {"concurrent_jobs": 1}})
    eng, bus, js, _, reg = make_engine(configsvc=cs)
    reg.update(hb("w1"))
    await eng.handle_job_request(JobRequest(job_id="j1", topic="job.default", tenant_id="tight"))
    with pytest.raises(RetryAfter):
        await eng.handle_job_request(JobRequest(job_id="j2", topic="job.default", tenant_id="tight"))
    # other tenants are unaffected
    await eng.handle_job_request(JobRequest(job_id="j3", topic="job.default", tenant_id="loose"))
    assert await js.get_state("j3") == "RUNNING"


async def test_engine_heartbeat_updates_registry():
    eng, bus, js, kv, reg = make_engine()
    await eng.start()
    await bus.publish(subj.HEARTBEAT, BusPacket.wrap(hb("w9", chip_count=8)))
    assert reg.get("w9").chip_count == 8


# ---------------------------------------------------------------- reconciler

async def test_reconciler_times_out_stale_jobs():
    eng, bus, js, kv, reg = make_engine()
    t = Timeouts(dispatch_timeout_s=0.0, running_timeout_s=0.0, scan_interval_s=999)
    rec = Reconciler(js, t)
    await js.set_state("j1", JobState.PENDING)
    await js.set_state("j1", JobState.RUNNING)
    await asyncio.sleep(0.01)
    n = await rec.run_once()
    assert n == 1
    assert await js.get_state("j1") == "TIMEOUT"


async def test_reconciler_deadline_expiry():
    eng, bus, js, kv, reg = make_engine()
    rec = Reconciler(js, Timeouts(dispatch_timeout_s=9999, running_timeout_s=9999))
    await js.set_state("j1", JobState.PENDING)
    await js.set_state("j1", JobState.RUNNING)
    await js.register_deadline("j1", int(time.time() * 1000) - 1000)
    n = await rec.run_once()
    assert n == 1 and await js.get_state("j1") == "TIMEOUT"


async def test_pending_replayer_redrives():
    eng, bus, js, kv, reg = make_engine()
    reg.update(hb("w1"))
    req = JobRequest(job_id="j1", topic="job.default")
    await js.put_request(req)
    await js.set_state("j1", JobState.PENDING)
    await asyncio.sleep(0.01)
    rep = PendingReplayer(eng, js, Timeouts(dispatch_timeout_s=0.0, pending_replay_s=0.0))
    n = await rep.run_once()
    assert n == 1
    assert await js.get_state("j1") == "RUNNING"


async def test_replayer_redispatches_wedged_scheduled():
    """A job persisted as SCHEDULED whose dispatch publish never happened
    (crash/bus blip) is re-driven by the replayer — the submit-path in-flight
    short-circuit intentionally ignores redeliveries for it (review finding)."""
    eng, bus, js, kv, reg = make_engine()
    reg.update(hb("w1"))
    req = JobRequest(job_id="j1", topic="job.default")
    await js.put_request(req)
    await js.set_state("j1", JobState.PENDING)
    await js.set_state("j1", JobState.SCHEDULED, fields={"dispatch_subject": "worker.w1.jobs"})
    await asyncio.sleep(0.01)
    # redelivered submit is a no-op (in-flight short-circuit)
    await eng.handle_job_request(req)
    assert await js.get_state("j1") == "SCHEDULED"
    assert not [p for s, p in bus.published if s == "worker.w1.jobs"]
    # the replayer recovers it through the dispatch leg
    rep = PendingReplayer(eng, js, Timeouts(dispatch_timeout_s=0.0, pending_replay_s=0.0))
    n = await rep.run_once()
    assert n == 1
    assert await js.get_state("j1") == "RUNNING"
    sent = [p for s, p in bus.published if s == "worker.w1.jobs"]
    assert sent and sent[0].job_request.job_id == "j1"
    # exhausting attempts lands in the DLQ instead of looping forever
    await js.put_request(JobRequest(job_id="j2", topic="job.default"))
    await js.set_state("j2", JobState.PENDING)
    await js.set_state("j2", JobState.SCHEDULED)
    await js.set_fields("j2", {"attempts": str(eng.max_attempts)})
    await rep.run_once()
    assert await js.get_state("j2") in ("FAILED", "DLQ", "DENIED") or \
        (await js.get_meta("j2")).get("error_code") == "MAX_RETRIES"


def test_naive_strategy():
    assert NaiveStrategy().pick_subject(JobRequest(job_id="j", topic="job.x")) == "job.x"


# ------------------------------------------------- review-finding regressions

async def test_approval_republish_not_deduped_on_bus():
    """Approval republish reuses the job_id on sys.job.submit; the bus msg-id
    must treat it as a distinct message (finding: dedupe dropped approvals)."""
    pol = {"rules": [{"id": "a", "match": {"topics": ["job.big"]}, "decision": "require_approval"}]}
    eng, bus, js, kv, reg = make_engine(pol)
    reg.update(hb("w1"))
    await eng.start()
    req = JobRequest(job_id="j1", topic="job.big")
    await bus.publish(subj.SUBMIT, BusPacket.wrap(req))
    assert await js.get_state("j1") == "APPROVAL_REQUIRED"
    approved = JobRequest(job_id="j1", topic="job.big", labels={"approval_granted": "true"})
    await bus.publish(subj.SUBMIT, BusPacket.wrap(approved))  # same subject+job_id
    assert await js.get_state("j1") == "RUNNING"


async def test_approval_hash_stable_under_constraints():
    """Stored decision hash must be computed before constraint env injection
    (finding: constrained approvals could never be faithfully republished)."""
    pol = {"rules": [{"id": "a", "match": {"topics": ["job.big"]}, "decision": "require_approval",
                      "constraints": {"max_chips": 2, "env": {"X": "1"}}}]}
    eng, bus, js, kv, reg = make_engine(pol)
    reg.update(hb("w1"))
    req = JobRequest(job_id="j1", topic="job.big")
    await eng.handle_job_request(req)
    rec = await js.get_safety_decision("j1")
    assert rec.job_hash == job_hash(JobRequest(job_id="j1", topic="job.big"))


async def test_throttle_does_not_burn_attempts():
    """Backpressure redeliveries must not consume the dispatch-attempt budget."""
    pol = {"rules": [{"id": "t", "match": {"topics": ["job.slow"]}, "decision": "throttle",
                      "throttle_delay_s": 0.001}]}
    eng, bus, js, kv, reg = make_engine(pol, max_attempts=2)
    from cordum_tpu.infra.bus import RetryAfter

    req = JobRequest(job_id="j1", topic="job.slow")
    for _ in range(5):
        with pytest.raises(RetryAfter):
            await eng.handle_job_request(req)
    assert (await js.get_meta("j1")).get("attempts", "0") == "0"
    assert await js.get_state("j1") == "PENDING"


async def test_preferred_worker_hint_respects_capabilities():
    reg = WorkerRegistry()
    reg.update(hb("small", pool="tpu", capabilities=["tpu"], chip_count=1))
    reg.update(hb("big", pool="tpu", capabilities=["tpu"], chip_count=8))
    pc = parse_pool_config({"topics": {"job.tpu": "tpu"}, "pools": {"tpu": {"requires": ["tpu"]}}})
    strat = LeastLoadedStrategy(reg, pc)
    req = JobRequest(job_id="j", topic="job.tpu", labels={"preferred_worker_id": "small"},
                     metadata=JobMetadata(requires=["chips:8"]))
    assert strat.pick_subject(req) == "worker.big.jobs"  # hint overridden: incapable


async def test_reconciler_lock_owner_checked():
    eng, bus, js, kv, reg = make_engine()
    t = Timeouts(dispatch_timeout_s=0.0, running_timeout_s=0.0, scan_interval_s=999)
    rec_a = Reconciler(js, t, instance_id="A")
    # another replica holds the singleton lock
    from cordum_tpu.controlplane.scheduler.reconciler import SINGLETON_LOCK

    await kv.setnx(SINGLETON_LOCK, b"B", ttl_s=60)
    assert await rec_a.run_once() == 0  # skipped
    assert (await kv.get(SINGLETON_LOCK)) == b"B"  # B's lock untouched


async def test_kernel_disabled_fragment_tenants_not_sticky(kv):
    """Deep-copy regression: disabled fragment tenants must disappear."""
    from cordum_tpu.controlplane.safetykernel.kernel import SafetyKernel
    from cordum_tpu.protocol.types import PolicyCheckRequest

    cs = ConfigService(kv)
    kernel = SafetyKernel(policy_doc={"tenants": {"default": {"allow_topics": ["job.*"]}}}, configsvc=cs)
    await kernel.reload()
    await cs.set("system", "policy/t2", {"enabled": True, "tenants": {"t2": {"allow_topics": ["job.extra"]}}})
    await kernel.reload()
    assert (await kernel.check(PolicyCheckRequest(topic="job.extra", tenant_id="t2"))).decision == "ALLOW"
    await cs.set("system", "policy/t2", {"enabled": False, "tenants": {"t2": {"allow_topics": ["job.extra"]}}})
    await kernel.reload()
    # t2 falls back to default tenant policy: job.extra not matching job.* single-token? it does match
    # use a topic outside default allowlist to see the revocation
    resp = await kernel.check(PolicyCheckRequest(topic="other.topic", tenant_id="t2"))
    assert resp.decision == "DENY"
