"""SDK client against the live gateway stack."""
import pytest

from cordum_tpu.sdk.client import ApiError, Client
from tests.test_gateway import GwStack


async def test_sdk_job_flow():
    async with GwStack() as s:
        c = Client(str(s.client.make_url("")), api_key="user-key")
        try:
            doc = await c.submit_job("job.work", {"n": 7})
            final = await c.wait_job(doc["job_id"])
            assert final["state"] == "SUCCEEDED"
            assert final["result"]["echo"] == {"n": 7}
            st = await c.status()
            assert st["bus"]
        finally:
            await c.close()


async def test_sdk_workflow_and_approvals():
    async with GwStack() as s:
        user = Client(str(s.client.make_url("")), api_key="user-key")
        admin = Client(str(s.client.make_url("")), api_key="admin-key")
        try:
            await user.put_workflow({
                "id": "sdkwf",
                "steps": {"gate": {"type": "approval"},
                          "go": {"topic": "job.work", "depends_on": ["gate"]}},
            })
            run = await user.start_run("sdkwf", {"x": 1})
            await admin.approve_step(run["run_id"], "gate")
            final = await user.wait_run(run["run_id"])
            assert final["status"] == "SUCCEEDED"
            tl = await user.run_timeline(run["run_id"])
            assert any(e["event"] == "approved" for e in tl)
            # job-level approvals
            doc = await user.submit_job("job.deploy.api", {})
            import asyncio

            for _ in range(50):
                st = await user.job_status(doc["job_id"])
                if st["state"] == "APPROVAL_REQUIRED":
                    break
                await asyncio.sleep(0.05)
            approvals = await admin.list_approvals()
            assert any(a["job_id"] == doc["job_id"] for a in approvals)
            with pytest.raises(ApiError):
                await user.approve_job(doc["job_id"])  # non-admin
            await admin.approve_job(doc["job_id"])
        finally:
            await user.close()
            await admin.close()


async def test_sdk_artifacts_and_context():
    async with GwStack() as s:
        from cordum_tpu.context.service import ContextService

        s.gw.context_svc = ContextService(s.kv)
        c = Client(str(s.client.make_url("")), api_key="user-key")
        try:
            up = await c.put_artifact(b"model-blob", retention="short")
            data = await c.get_artifact(up["artifact_id"])
            assert data == b"model-blob"
            await c.update_memory("m1", payload="hi", model_response="hello!")
            msgs = await c.build_window("m1", mode="CHAT", payload="next")
            assert [m["content"] for m in msgs] == ["hi", "hello!", "next"]
        finally:
            await c.close()
