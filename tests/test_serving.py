"""Serving subsystem tests (ISSUE 7, docs/SERVING.md): page allocator
invariants (exhaustion → admission, reuse never leaks, fragmentation-free),
paged decode == full-forward greedy, continuous-batching join/leave
equivalence, cancel-of-stateful-jobs, scheduler session affinity, gateway
session-key stamping, and the SDK streaming helper."""
import asyncio
import random

import pytest

from cordum_tpu.serving.engine import GenRequest, ServingEngine, SessionCancelled
from cordum_tpu.serving.pager import CacheExhausted, PageAllocator


# ---------------------------------------------------------------- allocator


def test_pager_alloc_free_roundtrip():
    a = PageAllocator(num_pages=8, page_size=4)
    assert a.capacity == 7  # page 0 is the null page, never allocatable
    assert a.pages_for(1) == 1 and a.pages_for(4) == 1 and a.pages_for(5) == 2
    p1 = a.alloc("s1", 3)
    assert len(p1) == 3 and a.NULL_PAGE not in p1
    assert a.free_pages == 4 and a.used_pages == 3
    assert a.owner_pages("s1") == p1
    # cumulative per-owner alloc (a session growing its footprint)
    p2 = a.alloc("s1", 2)
    assert a.owner_pages("s1") == p1 + p2
    assert a.free("s1") == 5
    assert a.free_pages == 7
    assert a.free("s1") == 0  # double-free is a benign no-op
    assert a.free("never-seen") == 0


def test_pager_exhaustion_is_all_or_nothing():
    a = PageAllocator(num_pages=6, page_size=4)
    a.alloc("s1", 3)
    with pytest.raises(CacheExhausted):
        a.alloc("s2", 3)  # only 2 free
    # the failed alloc must not strand partial pages
    assert a.free_pages == 2 and a.owner_pages("s2") == []
    assert a.stats.exhaustions == 1
    a.free("s1")
    assert len(a.alloc("s2", 3)) == 3  # retirement unblocks the waiter


def test_pager_pages_never_shared_and_reuse_after_random_frees():
    """Page-granular free lists cannot fragment: after freeing owners in a
    random order, the full capacity is allocatable again, and no page is
    ever owned by two sessions at once."""
    rng = random.Random(7)
    a = PageAllocator(num_pages=33, page_size=8)
    owners = [f"s{i}" for i in range(8)]
    for i, o in enumerate(owners):
        a.alloc(o, (i % 4) + 1)
    seen: set[int] = set()
    for o in owners:
        pages = a.owner_pages(o)
        assert not (seen & set(pages)), "page owned by two sessions"
        seen.update(pages)
    rng.shuffle(owners)
    for o in owners:
        a.free(o)
    # no fragmentation: one owner can take every usable page
    assert len(a.alloc("big", a.capacity)) == 32
    assert a.free_pages == 0


def test_pager_rejects_degenerate_shapes():
    with pytest.raises(ValueError):
        PageAllocator(num_pages=1, page_size=4)  # null page only
    with pytest.raises(ValueError):
        PageAllocator(num_pages=4, page_size=0)
    a = PageAllocator(num_pages=4, page_size=4)
    with pytest.raises(ValueError):
        a.alloc("s", 0)


# ----------------------------------------------------- paged decode (jax)


@pytest.fixture(scope="module")
def llama_env():
    import jax
    import jax.numpy as jnp

    from cordum_tpu.models import llama
    from cordum_tpu.serving.backend import LlamaServingBackend

    # fp32: the equality oracle compares argmax between the paged path and
    # the full forward, whose accumulation orders differ — bf16 rounding can
    # flip near-ties and turn an exact-math test flaky
    cfg = llama.LlamaConfig(vocab_size=256, d_model=64, n_layers=2, n_heads=4,
                            n_kv_heads=2, d_ff=128, max_seq_len=128,
                            dtype=jnp.float32)
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    backend = LlamaServingBackend(
        cfg, num_pages=64, page_size=8, params_provider=lambda: params
    )
    return cfg, params, backend


def ref_greedy(cfg, params, prompt, n_new):
    """Sequential per-session decode oracle: full forward over the growing
    sequence, greedy argmax."""
    import jax.numpy as jnp

    from cordum_tpu.models import llama

    toks, out = list(prompt), []
    for _ in range(n_new):
        logits = llama.forward(params, jnp.asarray([toks], jnp.int32), cfg)
        nxt = int(jnp.argmax(logits[0, -1]))
        out.append(nxt)
        toks.append(nxt)
    return out


def paged_greedy(backend, alloc, owner, prompt, n_new):
    pages = alloc.alloc(owner, alloc.pages_for(len(prompt) + n_new))
    first = backend.prefill(prompt, pages)
    out, pos, last = [first], len(prompt), first
    for _ in range(n_new - 1):
        (nxt,) = backend.decode([(last, pos, pages)])
        pos, last = pos + 1, int(nxt)
        out.append(last)
    return out


def test_paged_decode_matches_full_forward(llama_env):
    """Prefill + paged decode steps reproduce full-forward greedy exactly —
    the paged KV cache is a cache, not an approximation."""
    cfg, params, be = llama_env
    alloc = PageAllocator(be.num_pages, be.page_size)
    # the 9-token prompt spans two pages (page_size=8): the multi-page
    # prefill scatter path is covered, not just single-page sessions
    for i, prompt in enumerate([[5, 9, 17, 3], [100, 42],
                                [7, 3, 11, 19, 2, 5, 23, 1, 13]]):
        assert paged_greedy(be, alloc, f"s{i}", prompt, 6) == ref_greedy(
            cfg, params, prompt, 6
        )


def test_ragged_batch_decode_matches_per_session(llama_env):
    """One ragged decode call over sessions of different lengths returns the
    same next token each would get decoding alone."""
    cfg, params, be = llama_env
    alloc = PageAllocator(be.num_pages, be.page_size)
    sessions = []
    for i, prompt in enumerate([[3, 1, 4, 1, 5], [9, 2], [6, 5, 3, 5, 8, 9, 7]]):
        pages = alloc.alloc(f"r{i}", alloc.pages_for(len(prompt) + 4))
        first = be.prefill(prompt, pages)
        sessions.append([first, len(prompt), pages, prompt, [first]])
    for _ in range(3):
        batch = be.decode([(s[0], s[1], s[2]) for s in sessions])
        for s, tok in zip(sessions, batch):
            s[0], s[1] = int(tok), s[1] + 1
            s[4].append(int(tok))
    for s in sessions:
        assert s[4] == ref_greedy(cfg, params, s[3], 4)


def test_page_reuse_never_leaks_across_sessions(llama_env):
    """Freed pages return to the pool dirty; a later owner's decode must be
    bit-identical to a fresh-cache run (stale K/V is unreachable through the
    causal mask + its own page table)."""
    cfg, params, be = llama_env
    alloc = PageAllocator(be.num_pages, be.page_size)
    # session A dirties a large footprint, then retires
    a_out = paged_greedy(be, alloc, "A", [11, 22, 33, 44, 55, 66], 8)
    assert alloc.free("A") > 0
    # session B reuses A's pages (FIFO free list hands them straight back)
    b_out = paged_greedy(be, alloc, "B", [200, 100, 50], 8)
    assert b_out == ref_greedy(cfg, params, [200, 100, 50], 8)
    assert b_out != a_out  # sanity: different conversations
    # and A again, over B's leavings, still exact
    alloc.free("B")
    assert paged_greedy(be, alloc, "A2", [11, 22, 33, 44, 55, 66], 8) == a_out


def test_ragged_mixed_prefill_decode_matches_sequential_property(llama_env):
    """Property (ISSUE 11): a randomized schedule of mixed prefill chunks +
    decode steps through the single ragged entry point is logit-identical
    (fp32 argmax) to per-session sequential prefill + padded decode —
    across varying prompt lengths (incl. multi-page), random chunk splits,
    and sessions joining and leaving mid-stream."""
    from cordum_tpu.serving.backend import StepEntry

    cfg, params, be = llama_env
    rng = random.Random(11)
    alloc = PageAllocator(be.num_pages, be.page_size)
    specs = []
    for i in range(6):
        plen = rng.randint(1, 2 * be.page_size + 3)  # spans 1-3 pages
        specs.append({
            "key": f"p{i}",
            "prompt": [rng.randrange(cfg.vocab_size) for _ in range(plen)],
            "n_new": rng.randint(1, 5),
        })
    waiting = list(specs)
    live: list[dict] = []
    out: dict[str, list[int]] = {s["key"]: [] for s in specs}
    guard = 0
    while waiting or live:
        guard += 1
        assert guard < 500, "schedule failed to converge"
        for _ in range(rng.randint(0, 2)):  # joins mid-stream
            if not waiting:
                break
            s = dict(waiting.pop(0), fed=0, pos=0, last=None)
            total = len(s["prompt"]) + s["n_new"]
            s["pages"] = alloc.alloc(s["key"], alloc.pages_for(total))
            live.append(s)
        if not live:
            continue
        entries, rows = [], []
        budget = be.max_batch_tokens
        for s in live:
            if budget <= 0:
                break
            if s["fed"] < len(s["prompt"]):  # prefill chunk, random split
                chunk = min(budget, rng.randint(1, len(s["prompt"]) - s["fed"]))
                completes = s["fed"] + chunk == len(s["prompt"])
                entries.append(StepEntry(
                    tokens=s["prompt"][s["fed"]:s["fed"] + chunk],
                    start=s["fed"], pages=s["pages"], sample=completes,
                    phase="prefill", key=s["key"]))
                s["fed"] += chunk
                budget -= chunk
            else:  # decode row
                entries.append(StepEntry(
                    tokens=[s["last"]], start=s["pos"], pages=s["pages"],
                    sample=True, phase="decode", key=s["key"]))
                budget -= 1
            rows.append(s)
        for s, tok in zip(rows, be.step(entries)):
            if tok is None:
                continue  # mid-prompt chunk
            if s["last"] is None:  # prefill completion: the first token
                s["pos"] = len(s["prompt"])
            else:
                s["pos"] += 1
            s["last"] = int(tok)
            out[s["key"]].append(int(tok))
        for s in [s for s in live if len(out[s["key"]]) >= s["n_new"]]:
            live.remove(s)  # leaves mid-stream free pages for reuse
            alloc.free(s["key"])
    for s in specs:
        assert out[s["key"]] == ref_greedy(cfg, params, s["prompt"],
                                           s["n_new"]), s["key"]


def test_ragged_single_program_no_recompile_cliff(llama_env):
    """Any mix of prompt lengths, batch widths and join/leave patterns
    compiles exactly ONE XLA program — the bucket-recompile cliff is gone,
    and ``cordum_serving_compile_total`` is the gated proof."""
    from cordum_tpu.infra.metrics import Metrics
    from cordum_tpu.serving.backend import LlamaServingBackend

    cfg, params, _ = llama_env
    metrics = Metrics()
    be = LlamaServingBackend(cfg, num_pages=64, page_size=8,
                             params_provider=lambda: params, metrics=metrics)
    alloc = PageAllocator(be.num_pages, be.page_size)
    # the old backend compiled one program per prompt-length bucket plus
    # one per pow2 decode-batch bucket; this mix would have cost >= 6
    sessions = []
    for i, plen in enumerate((1, 3, 9, 17)):
        prompt = [(7 * i + j) % cfg.vocab_size for j in range(plen)]
        pages = alloc.alloc(f"c{i}", alloc.pages_for(plen + 4))
        first = be.prefill(prompt, pages)
        sessions.append((first, plen, pages))
    for width in (1, 2, 4, 3):  # ragged join/leave widths, incl. non-pow2
        be.decode([(t, p, pg) for t, p, pg in sessions[:width]])
    assert be.compiled_programs() == 1
    assert metrics.serving_compiles.value(entry="ragged") == 1
    assert be.last_step_compiled is False  # steady state by now


# -------------------------------------------- engine (fake backend, fast)


class FakeBackend:
    """Deterministic integer-arithmetic backend implementing the ragged
    ``step()`` interface: prefill chunks accumulate a per-session prompt
    sum, the completing chunk samples ``(sum(prompt) * 3 + len(prompt)) %
    251``, and a decode row samples ``(last * 3 + pos) % 251``.  Tracks
    per-step row counts and supports an optional step delay so cancel
    tests get a window."""

    def __init__(self, num_pages=16, page_size=4, max_context=64,
                 step_delay=0.0, max_seqs=16, max_batch_tokens=32):
        self.num_pages = num_pages
        self.page_size = page_size
        self.max_context = max_context
        self.max_seqs = max_seqs
        self.max_batch_tokens = max_batch_tokens
        self.step_delay = step_delay
        self.steps = 0
        self.decode_batches: list[int] = []  # rows per mixed step
        self.prefills = 0  # completed prompts
        self.prefill_chunks = 0
        self.last_step_compiled = False
        self._fed: dict[str, tuple[int, int]] = {}  # key -> (sum, count)

    def step(self, entries):
        import time as _t

        if self.step_delay:
            _t.sleep(self.step_delay)
        # the static-shape contract the real backend enforces
        assert len(entries) <= self.max_seqs, "max_seqs exceeded"
        assert sum(len(e.tokens) for e in entries) <= self.max_batch_tokens, \
            "flat token budget exceeded"
        self.last_step_compiled = self.steps == 0  # one program, one compile
        self.steps += 1
        self.decode_batches.append(len(entries))
        out = []
        for e in entries:
            if e.phase == "prefill":
                s, c = self._fed.get(e.key, (0, 0))
                s, c = s + sum(e.tokens), c + len(e.tokens)
                self._fed[e.key] = (s, c)
                self.prefill_chunks += 1
                if e.sample:
                    self.prefills += 1
                    out.append((s * 3 + c) % 251)
                else:
                    out.append(None)
            else:
                out.append((e.tokens[0] * 3 + e.start) % 251)
        return out


def fake_ref(prompt, n_new):
    out = [(sum(prompt) * 3 + len(prompt)) % 251]
    pos = len(prompt)
    for _ in range(n_new - 1):
        out.append((out[-1] * 3 + pos) % 251)
        pos += 1
    return out


async def run_blocking(fn, *args):
    return await asyncio.get_running_loop().run_in_executor(None, fn, *args)


async def test_engine_join_leave_matches_sequential():
    """Sessions joining and retiring mid-flight get exactly the tokens a
    sequential per-session decode would produce — continuous batching is a
    scheduling change, not a math change."""
    # the small decode delay keeps sessions in flight long enough that the
    # staggered joiners actually share steps with the early ones
    be = FakeBackend(num_pages=32, step_delay=0.005)
    eng = ServingEngine(be, run_blocking=run_blocking, max_sessions=8,
                        max_new_tokens_cap=64, max_concurrent_prefills=2)

    async def one(job_id, prompt, n_new, delay):
        await asyncio.sleep(delay)
        return await eng.submit(
            GenRequest(prompt=prompt, max_new_tokens=n_new, stream=False),
            job_id=job_id,
        )

    specs = [("a", [1, 2, 3], 12, 0.0), ("b", [4, 5], 4, 0.01),
             ("c", [9, 9, 9, 9], 8, 0.02), ("d", [7], 3, 0.05)]
    outs = await asyncio.wait_for(
        asyncio.gather(*(one(j, p, n, d) for j, p, n, d in specs)), timeout=20
    )
    for (job_id, prompt, n_new, _), out in zip(specs, outs):
        assert out["tokens"] == fake_ref(prompt, n_new), job_id
        assert out["finish_reason"] == "length"
    assert max(be.decode_batches) >= 2, "sessions never actually shared a step"
    assert eng.allocator.free_pages == eng.allocator.capacity  # all freed
    assert eng.stats.retired == 4 and eng.stats.failed == 0
    await eng.stop()


async def test_engine_chunked_prefill_rides_decode_steps():
    """A prompt longer than the flat-buffer budget prefills in chunks
    across several mixed steps while another session keeps decoding — both
    finish with exactly their sequential tokens (chunked prefill is a
    scheduling change, not a math change)."""
    be = FakeBackend(num_pages=64, page_size=4, max_context=128,
                     max_batch_tokens=8, step_delay=0.002)
    eng = ServingEngine(be, run_blocking=run_blocking, max_sessions=4,
                        max_new_tokens_cap=64)
    long_prompt = list(range(1, 31))  # 30 tokens >> the 8-token budget

    async def one(job_id, prompt, n_new, delay):
        await asyncio.sleep(delay)
        return await eng.submit(
            GenRequest(prompt=prompt, max_new_tokens=n_new, stream=False),
            job_id=job_id,
        )

    outs = await asyncio.wait_for(asyncio.gather(
        one("fast", [2, 3], 20, 0.0),
        one("slow", long_prompt, 4, 0.01),
    ), timeout=20)
    assert outs[0]["tokens"] == fake_ref([2, 3], 20)
    assert outs[1]["tokens"] == fake_ref(long_prompt, 4)
    # the long prompt really was chunked: sharing the 8-slot buffer with a
    # decode row leaves <= 7 tokens per chunk, so 30 tokens need >= 5
    assert be.prefill_chunks >= 5
    assert eng.stats.prefill_tokens == 30 + 2
    assert max(be.decode_batches) >= 2, "prefill never rode a decode step"
    await eng.stop()


async def test_engine_admission_queue_on_exhaustion():
    """A cache sized for one session at a time admits FIFO as pages free —
    exhaustion delays admission, it never fails an accepted session."""
    be = FakeBackend(num_pages=5, page_size=4)  # 4 usable pages
    eng = ServingEngine(be, run_blocking=run_blocking, max_sessions=8,
                        max_new_tokens_cap=64)
    # each session needs 3 pages (prompt 4 + 6 new = 10 tokens) → one at a time
    outs = await asyncio.wait_for(
        asyncio.gather(*(
            eng.submit(GenRequest(prompt=[i, i, i, i], max_new_tokens=6,
                                  stream=False), job_id=f"x{i}")
            for i in range(3)
        )),
        timeout=20,
    )
    for i, out in enumerate(outs):
        assert out["tokens"] == fake_ref([i, i, i, i], 6)
    assert eng.stats.admission_waits > 0  # the queue actually formed
    assert max(be.decode_batches) == 1  # pages, not slots, were the limit
    # an accepted-but-impossible footprint is rejected upfront, not queued
    # (20 tokens fit the page-table width but need 5 of the 4 usable pages)
    with pytest.raises(ValueError, match="KV pages"):
        await eng.submit(GenRequest(prompt=[1] * 12, max_new_tokens=8),
                         job_id="huge")
    await eng.stop()


async def test_engine_eos_stops_early():
    be = FakeBackend()
    eng = ServingEngine(be, run_blocking=run_blocking)
    seq = fake_ref([2, 3], 16)
    eos = seq[2]  # third generated token
    out = await asyncio.wait_for(
        eng.submit(GenRequest(prompt=[2, 3], max_new_tokens=16, eos_token=eos,
                              stream=False), job_id="e"),
        timeout=10,
    )
    assert out["tokens"] == seq[:3] and out["finish_reason"] == "eos"
    await eng.stop()


async def test_engine_cancel_pending_and_active_frees_pages():
    be = FakeBackend(num_pages=64, max_context=512, step_delay=0.02)
    eng = ServingEngine(be, run_blocking=run_blocking, max_sessions=4,
                        max_new_tokens_cap=600)
    live = asyncio.ensure_future(eng.submit(
        GenRequest(prompt=[1, 2], max_new_tokens=200, stream=False), job_id="live"))
    for _ in range(200):
        await asyncio.sleep(0.01)
        if eng.active_sessions() == 1:
            break
    assert eng.active_sessions() == 1
    pages_held = eng.allocator.used_pages
    assert pages_held > 0
    # cancel a job that is only queued… (park it by filling max_sessions)
    assert eng.cancel("live") is True
    with pytest.raises(SessionCancelled):
        await asyncio.wait_for(live, timeout=10)
    for _ in range(100):  # the loop frees pages on its next tick
        await asyncio.sleep(0.01)
        if eng.allocator.used_pages == 0:
            break
    assert eng.allocator.used_pages == 0
    assert eng.cancel("live") is False  # already gone
    assert eng.cancel("never-existed") is False
    await eng.stop()


async def test_engine_rejects_over_context_request_without_killing_batch():
    """A request longer than the backend's static page-table width fails
    alone at submit — it must never become a session, where its first decode
    step would raise and retire every in-flight conversation on the worker."""
    be = FakeBackend(num_pages=256, page_size=4, max_context=32,
                     step_delay=0.005)
    eng = ServingEngine(be, run_blocking=run_blocking, max_sessions=8,
                        max_new_tokens_cap=600)
    live = asyncio.ensure_future(eng.submit(
        GenRequest(prompt=[1, 2, 3], max_new_tokens=12, stream=False),
        job_id="live"))
    for _ in range(200):
        await asyncio.sleep(0.005)
        if eng.active_sessions() == 1:
            break
    assert eng.active_sessions() == 1
    # the arena has room (10 of 255 pages) — only the table width bars it
    assert eng.allocator.pages_for(40) <= eng.allocator.free_pages
    with pytest.raises(ValueError, match="max_context"):
        await eng.submit(GenRequest(prompt=[9] * 20, max_new_tokens=20,
                                    stream=False), job_id="huge")
    # the in-flight session is untouched by the rejection
    out = await asyncio.wait_for(live, timeout=10)
    assert out["tokens"] == fake_ref([1, 2, 3], 12)
    assert eng.stats.failed == 0
    await eng.stop()


async def test_engine_cancel_pending_counts_in_retirement_metric():
    """Cancelling a still-queued session moves the retirement metric the
    same way the prefilling/decoding cancel paths do (both ride _retire)."""
    from cordum_tpu.infra.metrics import Metrics

    metrics = Metrics()
    be = FakeBackend(num_pages=64, max_context=512, step_delay=0.02)
    eng = ServingEngine(be, run_blocking=run_blocking, max_sessions=1,
                        max_new_tokens_cap=600, metrics=metrics)
    live = asyncio.ensure_future(eng.submit(
        GenRequest(prompt=[1], max_new_tokens=100, stream=False),
        job_id="live"))
    for _ in range(200):
        await asyncio.sleep(0.01)
        if eng.active_sessions() == 1:
            break
    assert eng.active_sessions() == 1
    queued = asyncio.ensure_future(eng.submit(
        GenRequest(prompt=[2], max_new_tokens=4, stream=False),
        job_id="queued"))
    for _ in range(100):
        await asyncio.sleep(0.005)
        if eng.queue_depth() == 1:
            break
    assert eng.queue_depth() == 1  # parked behind max_sessions=1
    assert eng.cancel("queued") is True
    with pytest.raises(SessionCancelled):
        await asyncio.wait_for(queued, timeout=5)
    assert eng.stats.cancelled == 1
    assert metrics.serving_retired.value(reason="cancelled") == 1
    assert eng.cancel("live") is True
    with pytest.raises(SessionCancelled):
        await asyncio.wait_for(live, timeout=10)
    assert metrics.serving_retired.value(reason="cancelled") == 2
    await eng.stop()


async def test_parts_tolerates_malformed_max_new_tokens():
    """A non-numeric max_new_tokens is not a session: parts() returns None
    so the job falls through to the handler path's descriptive failure."""
    eng = ServingEngine(FakeBackend(), run_blocking=run_blocking)
    good = {"op": "llm.generate", "tokens": [1, 2]}
    assert eng.parts(good) is not None
    for bad in ("abc", [16], {"n": 16}, "12.5"):
        assert eng.parts({**good, "max_new_tokens": bad}) is None, bad
    await eng.stop()


async def test_engine_stop_evicts_everything():
    be = FakeBackend(num_pages=64, max_context=512, step_delay=0.02)
    eng = ServingEngine(be, run_blocking=run_blocking, max_sessions=2,
                        max_new_tokens_cap=600)
    futs = [asyncio.ensure_future(eng.submit(
        GenRequest(prompt=[i], max_new_tokens=100, stream=False), job_id=f"s{i}"))
        for i in range(4)]  # 2 admitted, 2 pending
    await asyncio.sleep(0.1)
    await eng.stop()
    for f in futs:
        with pytest.raises((SessionCancelled, asyncio.CancelledError)):
            await asyncio.wait_for(f, timeout=5)
    assert eng.allocator.used_pages == 0
    with pytest.raises(RuntimeError):
        await eng.submit(GenRequest(prompt=[1]), job_id="late")


# ------------------------------------------------------- session affinity


def _affinity_fixture(native=False):
    from cordum_tpu.controlplane.scheduler.strategy import LeastLoadedStrategy
    from cordum_tpu.infra.config import parse_pool_config
    from cordum_tpu.infra.registry import WorkerRegistry

    reg = WorkerRegistry()
    pc = parse_pool_config({"topics": {"job.tpu.generate": "tpu"},
                            "pools": {"tpu": {"requires": []}}})
    return reg, LeastLoadedStrategy(reg, pc, native=native)


def test_strategy_session_affinity_sticks_and_migrates():
    from cordum_tpu.protocol.types import Heartbeat, JobRequest, LABEL_SESSION_KEY

    reg, strat = _affinity_fixture()
    for wid, active in (("w-a", 0), ("w-b", 1)):
        reg.update(Heartbeat(worker_id=wid, pool="tpu", active_jobs=active,
                             max_parallel_jobs=16))
    req = JobRequest(job_id="t1", topic="job.tpu.generate",
                     labels={LABEL_SESSION_KEY: "conv-1"})
    assert strat.pick_subject(req) == "worker.w-a.jobs"
    assert strat.session_affinity_new == 1
    # sticky across turns even when the holder grows busier (its KV pages
    # are there; re-routing would orphan them)
    reg.update(Heartbeat(worker_id="w-a", pool="tpu", active_jobs=9,
                         max_parallel_jobs=16))
    for _ in range(5):
        assert strat.pick_subject(req) == "worker.w-a.jobs"
    assert strat.session_affinity_hits == 5
    # sessionless jobs still load-balance
    assert strat.pick_subject(
        JobRequest(job_id="t2", topic="job.tpu.generate")) == "worker.w-b.jobs"
    # overload evicts: the session migrates (counted as a miss, not new)
    reg.update(Heartbeat(worker_id="w-a", pool="tpu", active_jobs=16,
                         max_parallel_jobs=16))
    assert strat.pick_subject(req) == "worker.w-b.jobs"
    assert strat.session_affinity_misses == 1


def test_strategy_session_ttl_outlives_batch_ttl():
    """The session TTL is sized to conversation think-time: an entry too old
    for batch affinity still sticks, and only SESSION_AFFINITY_TTL_S drops
    it (a drop then counts as a migration)."""
    from cordum_tpu.controlplane.scheduler.strategy import (
        _SESSION_PREFIX, BATCH_AFFINITY_TTL_S, SESSION_AFFINITY_TTL_S,
    )
    from cordum_tpu.protocol.types import Heartbeat, JobRequest, LABEL_SESSION_KEY

    assert SESSION_AFFINITY_TTL_S > BATCH_AFFINITY_TTL_S
    reg, strat = _affinity_fixture()
    reg.update(Heartbeat(worker_id="w-a", pool="tpu", max_parallel_jobs=16))
    req = JobRequest(job_id="t", topic="job.tpu.generate",
                     labels={LABEL_SESSION_KEY: "conv-9"})
    strat.pick_subject(req)
    akey = _SESSION_PREFIX + "conv-9"
    wid, stamped = strat._affinity[akey]
    # older than the batch TTL → still a hit
    strat._affinity[akey] = (wid, stamped - BATCH_AFFINITY_TTL_S - 1)
    strat.pick_subject(req)
    assert strat.session_affinity_hits == 1
    # older than the session TTL → dropped, rerouted as a miss
    strat._affinity[akey] = (wid, stamped - SESSION_AFFINITY_TTL_S - 1)
    strat.pick_subject(req)
    assert strat.session_affinity_misses == 1


def test_session_keys_never_collide_with_batch_keys():
    """A session id equal to a batch key routes through its own namespaced
    affinity entry (an adversarial session_id cannot hijack batch routing)."""
    from cordum_tpu.controlplane.scheduler.strategy import _SESSION_PREFIX
    from cordum_tpu.protocol.types import (
        Heartbeat, JobRequest, LABEL_BATCH_KEY, LABEL_SESSION_KEY,
    )

    reg, strat = _affinity_fixture()
    reg.update(Heartbeat(worker_id="w-a", pool="tpu", max_parallel_jobs=16))
    strat.pick_subject(JobRequest(job_id="b", topic="job.tpu.generate",
                                  labels={LABEL_BATCH_KEY: "embed"}))
    strat.pick_subject(JobRequest(job_id="s", topic="job.tpu.generate",
                                  labels={LABEL_SESSION_KEY: "embed"}))
    assert "embed" in strat._affinity
    assert _SESSION_PREFIX + "embed" in strat._affinity


# ------------------------------------------------- worker e2e (real stack)


async def settle(bus, rounds=6):
    for _ in range(rounds):
        await bus.drain()
        await asyncio.sleep(0.02)


def make_stack():
    from tests.test_batching import make_stack as _ms

    return _ms()


def make_serving_worker(bus, ms, *, backend=None, metrics=None, **eng_kw):
    from cordum_tpu.worker.handlers import TPUCompute, make_tpu_handlers
    from cordum_tpu.worker.runtime import Worker

    w = Worker(bus=bus, store=ms, worker_id="w-srv", pool="tpu",
               topics=["job.tpu.>"], capabilities=["tpu"],
               heartbeat_interval_s=999)
    compute = TPUCompute(tp=1)
    w.register_default(make_tpu_handlers(compute))
    eng = ServingEngine(backend or FakeBackend(num_pages=64),
                        run_blocking=w.run_in_executor, metrics=metrics,
                        tracer=w.tracer, **eng_kw)
    w.attach_serving(eng)
    return w


async def test_worker_generate_e2e_stream_and_terminal_result():
    """llm.generate through the full pipeline: tokens stream as progress
    packets, the terminal result carries the whole list, the scheduler does
    NOT persist per-token events, serving metrics move, and KV pages are
    freed on retirement."""
    from cordum_tpu.infra.metrics import Metrics
    from cordum_tpu.protocol import subjects as subj
    from cordum_tpu.protocol.types import (
        BusPacket, JobRequest, STATUS_HINT_STREAM,
    )

    kv, bus, js, ms, eng = make_stack()
    await eng.start()
    metrics = Metrics()
    w = make_serving_worker(bus, ms, metrics=metrics, max_sessions=4)
    await w.start()
    await settle(bus)
    streams: dict[str, list[int]] = {}

    async def ptap(subject, pkt):
        pr = pkt.job_progress
        if pr is not None and pr.status_hint == STATUS_HINT_STREAM:
            streams.setdefault(pr.job_id, []).extend(pr.tokens)

    await bus.subscribe(subj.PROGRESS, ptap)
    n = 3
    for i in range(n):
        jid = f"g{i}"
        ptr = await ms.put_context(jid, {
            "op": "llm.generate", "tokens": [i + 1, 5, 9],
            "max_new_tokens": 6, "session_id": f"conv-{i}",
        })
        await bus.publish(subj.SUBMIT, BusPacket.wrap(
            JobRequest(job_id=jid, topic="job.tpu.generate", context_ptr=ptr)))
    for _ in range(300):
        await settle(bus, rounds=2)
        states = [await js.get_state(f"g{i}") for i in range(n)]
        if all(s == "SUCCEEDED" for s in states):
            break
    assert all(s == "SUCCEEDED" for s in states), states
    for i in range(n):
        res = await ms.get_result(f"g{i}")
        assert res["tokens"] == fake_ref([i + 1, 5, 9], 6)
        assert res["session_key"] == f"conv-{i}"
        # the stream and the terminal result agree token-for-token
        assert streams[f"g{i}"] == res["tokens"]
        # per-token stream packets are transport, never job-store events
        evts = await js.events(f"g{i}")
        assert not any(e.get("event") == "progress" for e in evts), evts
    assert w.serving.allocator.used_pages == 0
    assert metrics.serving_admitted.value() >= n
    assert metrics.serving_retired.value(reason="finished") >= n
    await w.stop()
    await eng.stop()


async def test_worker_cancel_inflight_generate_frees_pages():
    """sys.job.cancel of a decoding llm.generate session evicts it from the
    loop, frees its KV pages and publishes CANCELLED (the stateful mirror of
    the batcher's cancel-while-queued)."""
    from cordum_tpu.protocol import subjects as subj
    from cordum_tpu.protocol.types import BusPacket, JobCancel, JobRequest

    kv, bus, js, ms, eng = make_stack()
    await eng.start()
    w = make_serving_worker(bus, ms,
                            backend=FakeBackend(num_pages=64, max_context=512,
                                                step_delay=0.02),
                            max_sessions=4, max_new_tokens_cap=600)
    await w.start()
    await settle(bus)
    ptr = await ms.put_context("gc", {
        "op": "llm.generate", "tokens": [1, 2, 3], "max_new_tokens": 200,
        "session_id": "conv-c",
    })
    await bus.publish(subj.SUBMIT, BusPacket.wrap(
        JobRequest(job_id="gc", topic="job.tpu.generate", context_ptr=ptr)))
    for _ in range(300):
        await asyncio.sleep(0.02)
        if w.serving.active_sessions() == 1:
            break
    assert w.serving.active_sessions() == 1, "session never started decoding"
    assert w.serving.allocator.used_pages > 0
    await bus.publish(subj.CANCEL, BusPacket.wrap(JobCancel(job_id="gc", reason="test")))
    for _ in range(300):
        await asyncio.sleep(0.02)
        if await js.get_state("gc") == "CANCELLED":
            break
    assert await js.get_state("gc") == "CANCELLED"
    for _ in range(100):
        await asyncio.sleep(0.01)
        if w.serving.allocator.used_pages == 0:
            break
    assert w.serving.allocator.used_pages == 0
    assert w.serving.active_sessions() == 0
    await w.stop()
    await eng.stop()


async def test_worker_invalid_generate_payload_fails_pointedly():
    """A malformed llm.generate payload is not a session: it takes the
    per-job handler path and fails with the op's own error."""
    from cordum_tpu.protocol import subjects as subj
    from cordum_tpu.protocol.types import BusPacket, JobRequest

    kv, bus, js, ms, eng = make_stack()
    await eng.start()
    w = make_serving_worker(bus, ms)
    await w.start()
    await settle(bus)
    bad = {
        "gbad": {"op": "llm.generate", "tokens": "oops"},
        "gbad2": {"op": "llm.generate", "tokens": [1, 2],
                  "max_new_tokens": "lots"},
    }
    for jid, payload in bad.items():
        ptr = await ms.put_context(jid, payload)
        await bus.publish(subj.SUBMIT, BusPacket.wrap(
            JobRequest(job_id=jid, topic="job.tpu.generate", context_ptr=ptr)))
    for _ in range(100):
        await settle(bus)
        states = [await js.get_state(j) for j in bad]
        if all(s == "FAILED" for s in states):
            break
    for jid in bad:
        meta = await js.get_meta(jid)
        assert meta["state"] == "FAILED" and "tokens" in meta["error_message"]
    assert w.serving.stats.admitted == 0
    await w.stop()
    await eng.stop()


# --------------------------------------------------- gateway + sdk


async def test_gateway_stamps_session_key():
    from cordum_tpu.protocol.types import LABEL_SESSION_KEY
    from tests.test_gateway import GwStack

    async with GwStack() as s:
        r = await s.client.post("/api/v1/jobs", json={
            "topic": "job.work",
            "payload": {"op": "llm.generate", "tokens": [1, 2],
                        "session_id": "conv-42"},
        }, headers=s.h())
        assert r.status == 202
        doc = await r.json()
        await s.settle()
        # labels live on the persisted JobRequest, not the meta hash
        req = await s.job_store.get_request(doc["job_id"])
        assert req is not None
        assert req.labels[LABEL_SESSION_KEY] == "conv-42"
        # non-serving payloads must not grow the label
        r = await s.client.post("/api/v1/jobs", json={
            "topic": "job.work", "payload": {"op": "echo", "session_id": "x"},
        }, headers=s.h())
        doc2 = await r.json()
        await s.settle()
        req2 = await s.job_store.get_request(doc2["job_id"])
        assert req2 is not None and LABEL_SESSION_KEY not in (req2.labels or {})


class ServingGwStack:
    """Gateway + scheduler + a serving worker on job.tpu.generate, behind a
    live HTTP server (the SDK streaming helper's home turf)."""

    def __init__(self):
        from aiohttp.test_utils import TestClient, TestServer  # noqa: F401

        from tests.test_gateway import GwStack

        self.inner = GwStack()

    async def __aenter__(self):
        from cordum_tpu.controlplane.scheduler.strategy import LeastLoadedStrategy
        from cordum_tpu.infra.config import parse_pool_config

        s = self.inner
        # widen the scheduler's routing to the serving topic
        pc = parse_pool_config({
            "topics": {"job.work": "p", "job.tpu.generate": "tpu"},
            "pools": {"p": {}, "tpu": {}},
        })
        s.scheduler.strategy = LeastLoadedStrategy(s.scheduler.registry, pc)
        await s.__aenter__()
        self.worker = make_serving_worker(s.bus, s.mem, max_sessions=4)
        await self.worker.start()
        await s.settle()
        return self

    async def __aexit__(self, *exc):
        await self.worker.stop()
        await self.inner.__aexit__(*exc)


async def test_sdk_generate_streams_tokens():
    from cordum_tpu.sdk.client import Client

    async with ServingGwStack() as st:
        s = st.inner
        c = Client(str(s.client.make_url("")), api_key="user-key")
        try:
            got = [t async for t in c.generate(
                [1, 2, 3], session_id="conv-sdk", max_new_tokens=6,
                timeout_s=30)]
            assert got == fake_ref([1, 2, 3], 6)
            # non-streaming fallback: same contract, one burst
            got2 = [t async for t in c.generate(
                [1, 2, 3], session_id="conv-sdk", max_new_tokens=6,
                stream=False, timeout_s=30)]
            assert got2 == got
        finally:
            await c.close()
