"""Serving session failover (ISSUE 12, docs/SERVING.md §Migration, drain,
and failover): live KV-page migration (engine-level and worker-level,
token-identical to the sequential oracle), the (session, offset) resume
handshake under severed/asymmetric links, graceful drain with zero
CANCELLED sessions, scheduler-side crash failover with the forced-decode
resume prefix, and affinity eviction for dead/draining workers."""
import asyncio
import random

import pytest

from cordum_tpu.infra.config import Timeouts
from cordum_tpu.serving.engine import (
    GenRequest,
    ServingEngine,
    SessionMigrated,
    SessionRequeued,
)
from cordum_tpu.serving.migration import MigrationServer, migrate_session

from .test_serving import FakeBackend, fake_ref, run_blocking


class MigFakeBackend(FakeBackend):
    """FakeBackend + the migration contract: no KV arena, so export ships
    nothing and the receiver rebuilds the per-session prefill accumulator
    from the metadata (``restore_session``)."""

    def export_kv(self, pages, start_tok, end_tok):
        return []

    def import_kv(self, pages, records):
        return None

    def restore_session(self, key, seq, prefill_pos):
        self._fed[key] = (sum(seq[:prefill_pos]), prefill_pos)


def make_engine(**kw):
    kw.setdefault("num_pages", 64)
    kw.setdefault("max_context", 512)
    step_delay = kw.pop("step_delay", 0.005)
    eng_kw = {k: kw.pop(k) for k in ("max_sessions", "max_new_tokens_cap")
              if k in kw}
    be = MigFakeBackend(step_delay=step_delay, **kw)
    return ServingEngine(be, run_blocking=run_blocking,
                         max_new_tokens_cap=eng_kw.get("max_new_tokens_cap", 600),
                         max_sessions=eng_kw.get("max_sessions", 8))


def install_into(engine, results: dict):
    """A MigrationServer install callback adopting sessions into `engine`
    and collecting their final token lists into `results`."""

    async def install(meta, state, records):
        req = GenRequest(
            prompt=meta["prompt"], max_new_tokens=meta["max_new_tokens"],
            session_key=meta["session_key"], eos_token=meta["eos_token"],
            stream=meta["stream"], resume_tokens=meta["resume_tokens"],
        )
        fut = await engine.install_session(
            req, job_id=meta["job_id"], state=state, records=records)

        async def watch():
            try:
                results[meta["job_id"]] = await fut
            except Exception as e:  # noqa: BLE001 - surfaced by the test
                results[meta["job_id"]] = e

        asyncio.ensure_future(watch())

    return install


async def wait_until(cond, timeout_s=20.0, msg="condition"):
    import time as _t

    deadline = _t.monotonic() + timeout_s
    while _t.monotonic() < deadline:
        v = cond()
        if asyncio.iscoroutine(v):
            v = await v
        if v:
            return
        await asyncio.sleep(0.01)
    raise AssertionError(f"timed out waiting for {msg}")


# ------------------------------------------------------- engine-level moves


async def test_migrate_mid_decode_token_identical():
    """A session migrated mid-decode finishes on the target with EXACTLY
    the tokens an unmigrated run produces; the source's waiter sees
    SessionMigrated (publishes nothing) and both arenas end clean."""
    a, b = make_engine(step_delay=0.01), make_engine(step_delay=0.01)
    results: dict = {}
    srv = MigrationServer(install_into(b, results))
    await srv.start()
    src = asyncio.ensure_future(a.submit(
        GenRequest(prompt=[1, 2, 3], max_new_tokens=40, stream=False),
        job_id="m1"))
    await wait_until(
        lambda: (a.export_state("m1") or {}).get("pos", 0) >= 8,
        msg="session decoding")
    assert await migrate_session(a, "m1", srv.host, srv.port,
                                 metrics=a.metrics) is True
    with pytest.raises(SessionMigrated):
        await asyncio.wait_for(src, timeout=5)
    await wait_until(lambda: "m1" in results, msg="target finished")
    assert results["m1"] == fake_ref([1, 2, 3], 40)
    assert a.allocator.used_pages == 0
    assert a.stats.migrated_out == 1 and b.stats.migrated_in == 1
    await wait_until(lambda: b.allocator.used_pages == 0, msg="target freed")
    await a.stop(), await b.stop(), await srv.stop()


async def test_migrate_real_backend_matches_oracle():
    """Real paged-Llama KV pages move worker→worker at their true lengths
    and the resumed session reproduces the fp32 sequential oracle exactly —
    migration is a placement change, not a math change."""
    import jax
    import jax.numpy as jnp

    from cordum_tpu.models import llama
    from cordum_tpu.serving.backend import LlamaServingBackend

    from .test_serving import ref_greedy

    cfg = llama.LlamaConfig(vocab_size=256, d_model=64, n_layers=2, n_heads=4,
                            n_kv_heads=2, d_ff=128, max_seq_len=128,
                            dtype=jnp.float32)
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    bea = LlamaServingBackend(cfg, num_pages=64, page_size=8,
                              params_provider=lambda: params)
    beb = LlamaServingBackend(cfg, num_pages=64, page_size=8,
                              params_provider=lambda: params)
    a = ServingEngine(bea, run_blocking=run_blocking, max_new_tokens_cap=64)
    b = ServingEngine(beb, run_blocking=run_blocking, max_new_tokens_cap=64)
    results: dict = {}
    srv = MigrationServer(install_into(b, results))
    await srv.start()
    prompt = [7, 3, 11, 19, 2, 5, 23, 1, 13]  # spans two pages
    src = asyncio.ensure_future(a.submit(
        GenRequest(prompt=prompt, max_new_tokens=24, stream=False),
        job_id="r1"))
    # migrate once several pages are live (prompt prefilled + some decode)
    await wait_until(
        lambda: (a.export_state("r1") or {}).get("pos", 0) >= 12,
        timeout_s=120, msg="multi-page decode state")
    assert await migrate_session(a, "r1", srv.host, srv.port) is True
    with pytest.raises(SessionMigrated):
        await asyncio.wait_for(src, timeout=10)
    await wait_until(lambda: "r1" in results, timeout_s=120,
                     msg="target finished")
    assert results["r1"] == ref_greedy(cfg, params, prompt, 24)
    await a.stop(), await b.stop(), await srv.stop()


async def test_forced_decode_resume_matches_oracle_real_backend():
    """Crash failover resumes by prefilling prompt + already-streamed
    tokens (forced decode): on the real paged backend the continuation is
    token-identical to the uninterrupted fp32 oracle at every cut point."""
    import jax
    import jax.numpy as jnp

    from cordum_tpu.models import llama
    from cordum_tpu.serving.backend import LlamaServingBackend

    from .test_serving import ref_greedy

    cfg = llama.LlamaConfig(vocab_size=256, d_model=64, n_layers=2, n_heads=4,
                            n_kv_heads=2, d_ff=128, max_seq_len=128,
                            dtype=jnp.float32)
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    prompt = [41, 7, 99, 3]
    oracle = ref_greedy(cfg, params, prompt, 12)
    for cut in (1, 5, 11, 12):  # incl. resume-of-a-finished-session
        be = LlamaServingBackend(cfg, num_pages=64, page_size=8,
                                 params_provider=lambda: params)
        eng = ServingEngine(be, run_blocking=run_blocking,
                            max_new_tokens_cap=64)
        out = await asyncio.wait_for(eng.submit(
            GenRequest(prompt=prompt, max_new_tokens=12, stream=False,
                       resume_tokens=oracle[:cut]),
            job_id=f"resume-{cut}"), timeout=120)
        assert out["tokens"] == oracle, f"cut={cut}"
        await eng.stop()


async def test_migrate_random_points_property():
    """Property: migrating a session at ANY point of its lifetime —
    mid-prefill, right after the first token, deep into decode — yields
    the oracle token sequence (randomized over prompts and cut points)."""
    rng = random.Random(17)
    for trial in range(4):
        a, b = make_engine(step_delay=0.002), make_engine(step_delay=0.002)
        results: dict = {}
        srv = MigrationServer(install_into(b, results))
        await srv.start()
        plen = rng.randint(1, 12)
        prompt = [rng.randrange(1, 200) for _ in range(plen)]
        n_new = rng.randint(4, 60)
        cut = rng.randint(0, plen + n_new - 2)
        jid = f"p{trial}"
        src = asyncio.ensure_future(a.submit(
            GenRequest(prompt=prompt, max_new_tokens=n_new, stream=False),
            job_id=jid))
        await wait_until(
            lambda: (a.export_state(jid) or {}).get("pos", 0) >= min(cut, 1),
            msg="session live")
        moved = await migrate_session(a, jid, srv.host, srv.port)
        if moved:
            with pytest.raises(SessionMigrated):
                await asyncio.wait_for(src, timeout=10)
            await wait_until(lambda: jid in results, msg="target finished")
            got = results[jid]
        else:
            got = (await asyncio.wait_for(src, timeout=10))["tokens"]
        assert got == fake_ref(prompt, n_new), (trial, prompt, n_new, cut)
        await a.stop(), await b.stop(), await srv.stop()


async def test_migration_handshake_resumes_from_receiver_offset():
    """The (session, offset) handshake: a sender that lost its connection
    mid page-stream reconnects, hears the receiver's record count, and
    resumes from there — the receiver ends with each page exactly once."""
    from cordum_tpu.infra.frames import encode_frame, read_frame

    b = make_engine()
    results: dict = {}
    srv = MigrationServer(install_into(b, results))
    await srv.start()
    # a first, doomed connection delivers hello + 2 page records, then dies
    reader, writer = await asyncio.open_connection(srv.host, srv.port)
    writer.write(encode_frame(["hello", {"session": "h1", "meta": {}}]))
    await writer.drain()
    ok = await read_frame(reader)
    assert ok[0] == "ok" and ok[1]["offset"] == 0
    for i in range(2):
        writer.write(encode_frame(
            ["page", {"session": "h1", "offset": i, "rec": {"i": i}}]))
    await writer.drain()
    await asyncio.sleep(0.05)
    writer.close()  # link severed mid-transfer
    # the reconnect hears offset=2 and must NOT resend records 0-1
    reader, writer = await asyncio.open_connection(srv.host, srv.port)
    writer.write(encode_frame(["hello", {"session": "h1", "meta": {}}]))
    await writer.drain()
    ok = await read_frame(reader)
    assert ok[1]["offset"] == 2, "receiver forgot its partial records"
    # duplicates below the offset are dropped, the next record appends
    writer.write(encode_frame(
        ["page", {"session": "h1", "offset": 1, "rec": {"i": "dup"}}]))
    writer.write(encode_frame(
        ["page", {"session": "h1", "offset": 2, "rec": {"i": 2}}]))
    # a commit at the wrong offset is rejected (no silent page loss)
    writer.write(encode_frame(
        ["commit", {"session": "h1", "offset": 7, "state": {}, "delta": []}]))
    await writer.drain()
    err = await read_frame(reader)
    assert err[0] == "error" and "offset" in err[1]["msg"]
    writer.close()
    assert "h1" not in results
    await b.stop()
    await srv.stop()


async def test_migration_survives_asymmetric_partition():
    """A blackholed reply path (requests arrive, acks vanish — the
    asymmetric partition ChaosProxy now models per-direction) fails the
    migration CLEANLY: the sender times out, unfreezes, and the session
    finishes locally with the oracle tokens — never stranded, never
    double-owned."""
    from cordum_tpu.infra.chaos import ChaosProxy

    a, b = make_engine(step_delay=0.005), make_engine(step_delay=0.005)
    results: dict = {}
    srv = MigrationServer(install_into(b, results))
    await srv.start()
    proxy = ChaosProxy(srv.host, srv.port)
    await proxy.start()
    src = asyncio.ensure_future(a.submit(
        GenRequest(prompt=[4, 5, 6], max_new_tokens=30, stream=False),
        job_id="asym"))
    await wait_until(
        lambda: (a.export_state("asym") or {}).get("pos", 0) >= 6,
        msg="session decoding")
    proxy.blackhole("s2c")  # hello reaches the server; the ok never returns
    moved = await migrate_session(a, "asym", proxy.listen_host, proxy.port,
                                  timeout_s=0.5)
    assert moved is False
    # the session decodes on, unfrozen, to the exact oracle output
    out = await asyncio.wait_for(src, timeout=20)
    assert out["tokens"] == fake_ref([4, 5, 6], 30)
    assert "asym" not in results  # the half-arrived transfer never installed
    proxy.restore()
    await proxy.stop(), await a.stop(), await b.stop(), await srv.stop()


async def test_install_refusal_and_crashed_loop_requeue():
    """Satellite: a target at max_sessions refuses the install (sender
    falls back, session survives locally); a crashed decode loop requeues
    its live sessions as SessionRequeued instead of failing them."""
    a = make_engine(step_delay=0.005)
    b = make_engine(step_delay=0.005, max_sessions=1)
    results: dict = {}
    srv = MigrationServer(install_into(b, results))
    await srv.start()
    # fill b's only session slot
    busy = asyncio.ensure_future(b.submit(
        GenRequest(prompt=[9], max_new_tokens=50, stream=False), job_id="busy"))
    await wait_until(lambda: b.active_sessions() == 1, msg="b busy")
    src = asyncio.ensure_future(a.submit(
        GenRequest(prompt=[1, 1], max_new_tokens=30, stream=False), job_id="rf"))
    await wait_until(
        lambda: (a.export_state("rf") or {}).get("pos", 0) >= 3,
        msg="session decoding")
    assert await migrate_session(a, "rf", srv.host, srv.port) is False
    out = await asyncio.wait_for(src, timeout=20)  # finishes locally
    assert out["tokens"] == fake_ref([1, 1], 30)
    assert (await asyncio.wait_for(busy, timeout=20))["tokens"] == fake_ref([9], 50)

    # crashed decode loop: a poisoned capacity hook escapes the step loop —
    # live sessions come back as SessionRequeued (scheduler failover), not
    # FAILED (satellite 2: bounded by the attempts counter upstream)
    class Boom:
        def observe(self, *a, **kw):
            raise RuntimeError("observer exploded")

    c = make_engine(step_delay=0.005)
    c.capacity = Boom()
    victim = asyncio.ensure_future(c.submit(
        GenRequest(prompt=[2, 2], max_new_tokens=30, stream=False), job_id="vc"))
    with pytest.raises(SessionRequeued):
        await asyncio.wait_for(victim, timeout=20)
    assert c.stats.requeued == 1 and c.stats.failed == 0
    await a.stop(), await b.stop(), await c.stop(), await srv.stop()


# ------------------------------------------------- strategy/affinity (sat 1)


def test_strategy_evicts_affinity_for_dead_and_draining_workers():
    """Affinity entries die WITH their worker: an explicit evict_worker
    (deregistration), a draining heartbeat, and a silently vanished
    registry entry all reroute the session immediately — not after the
    120s TTL — and count in the evicted outcome."""
    from cordum_tpu.infra.metrics import Metrics
    from cordum_tpu.protocol.types import Heartbeat, JobRequest, LABEL_SESSION_KEY

    from .test_serving import _affinity_fixture

    reg, strat = _affinity_fixture()
    metrics = Metrics()
    strat.metrics = metrics
    for wid in ("w-a", "w-b"):
        reg.update(Heartbeat(worker_id=wid, pool="tpu", max_parallel_jobs=16))
    req = JobRequest(job_id="t", topic="job.tpu.generate",
                     labels={LABEL_SESSION_KEY: "conv-ev"})
    assert strat.pick_subject(req) == "worker.w-a.jobs"
    # 1. explicit eviction (what the engine does when a worker deregisters:
    # registry removal + affinity eviction together)
    reg.remove("w-a")
    assert strat.evict_worker("w-a") == 1
    assert strat.session_affinity_evicted == 1
    assert strat.pick_subject(req) == "worker.w-b.jobs"
    # 2. draining heartbeat: the sticky worker is draining → entry dropped
    reg.update(Heartbeat(worker_id="w-b", pool="tpu", max_parallel_jobs=16,
                         draining=True))
    assert strat.pick_subject(req) == "job.tpu.generate"  # no live worker left
    assert strat.session_affinity_evicted == 2
    # 3. vanished worker (missed heartbeats → registry dropped it)
    reg.update(Heartbeat(worker_id="w-c", pool="tpu", max_parallel_jobs=16))
    assert strat.pick_subject(req) == "worker.w-c.jobs"
    reg.remove("w-c")
    strat.pick_subject(req)
    assert strat.session_affinity_evicted == 3
    assert metrics.session_affinity.value(outcome="evicted") == 3


async def test_scheduler_deregisters_draining_worker_on_heartbeat():
    from cordum_tpu.protocol import subjects as subj
    from cordum_tpu.protocol.types import BusPacket, Heartbeat

    from .test_batching import make_stack

    kv, bus, js, ms, eng = make_stack()
    await eng.start()
    await bus.publish(subj.HEARTBEAT, BusPacket.wrap(
        Heartbeat(worker_id="w-d", pool="tpu", max_parallel_jobs=4)))
    await bus.drain()
    assert eng.registry.get("w-d") is not None
    await bus.publish(subj.HEARTBEAT, BusPacket.wrap(
        Heartbeat(worker_id="w-d", pool="tpu", max_parallel_jobs=4,
                  draining=True)))
    await bus.drain()
    assert eng.registry.get("w-d") is None
    await eng.stop()
    await bus.close()


# --------------------------------------------- worker e2e: drain + failover


def make_serving_worker(bus, ms, wid, *, step_delay=0.01, **eng_kw):
    from cordum_tpu.worker.handlers import TPUCompute, make_tpu_handlers
    from cordum_tpu.worker.runtime import Worker

    w = Worker(bus=bus, store=ms, worker_id=wid, pool="tpu",
               topics=["job.tpu.>"], capabilities=["tpu"],
               heartbeat_interval_s=999)
    compute = TPUCompute(tp=1)
    w.register_default(make_tpu_handlers(compute))
    eng = ServingEngine(
        MigFakeBackend(num_pages=64, max_context=512, step_delay=step_delay),
        run_blocking=w.run_in_executor, tracer=w.tracer,
        max_new_tokens_cap=600, **eng_kw)
    w.attach_serving(eng)
    return w


class StreamTap:
    """Assembles per-job token streams by offset, asserting any replayed
    prefix agrees with what was already streamed (exactly-once check)."""

    def __init__(self):
        self.streams: dict[str, list[int]] = {}

    async def __call__(self, subject, pkt):
        pr = pkt.job_progress
        if pr is None or pr.status_hint != "stream":
            return
        buf = self.streams.setdefault(pr.job_id, [])
        off = pr.offset if pr.offset >= 0 else len(buf)
        for i, t in enumerate(pr.tokens):
            idx = off + i
            if idx == len(buf):
                buf.append(int(t))
            elif idx < len(buf):
                assert buf[idx] == int(t), (
                    f"replayed token diverges at {idx}: {buf[idx]} vs {t}")


async def test_drain_migrates_sessions_zero_cancelled():
    """ISSUE 12 drain acceptance: draining a worker with live sessions
    completes with ZERO CANCELLED/FAILED sessions — every session
    live-migrates to the peer, finishes token-identical to the oracle, and
    the client-visible stream (offset-assembled) is exactly the oracle."""
    from cordum_tpu.protocol import subjects as subj
    from cordum_tpu.protocol.types import BusPacket, JobRequest

    from .test_batching import make_stack
    from .test_serving import settle

    kv, bus, js, ms, eng = make_stack()
    await eng.start()
    w1 = make_serving_worker(bus, ms, "w-dr1", step_delay=0.02)
    w2 = make_serving_worker(bus, ms, "w-dr2", step_delay=0.02)
    await w1.start()
    await w2.start()
    tap = StreamTap()
    await bus.subscribe(subj.PROGRESS, tap)
    await settle(bus)
    await w1.send_heartbeat()
    await w2.send_heartbeat()  # each worker learns the other's listener
    await settle(bus)
    n = 3
    jobs = {}
    for i in range(n):
        jid = f"dr{i}"
        prompt = [i + 1, 7, 3]
        jobs[jid] = prompt
        ptr = await ms.put_context(jid, {
            "op": "llm.generate", "tokens": prompt, "max_new_tokens": 60,
            "session_id": f"conv-dr{i}",
        })
        # pinned to w1 so the drain has real sessions to move
        await bus.publish(subj.SUBMIT, BusPacket.wrap(JobRequest(
            job_id=jid, topic="job.tpu.generate", context_ptr=ptr,
            labels={"preferred_worker_id": "w-dr1"})))
    await wait_until(lambda: w1.serving.active_sessions() == n,
                     msg="sessions decoding on w1")
    await wait_until(
        lambda: all(len(tap.streams.get(j, [])) >= 3 for j in jobs),
        msg="streams flowing")
    await w1.drain(timeout_s=30)
    assert w1.serving.session_count == 0
    assert w1.serving.stats.migrated_out == n
    assert w1.serving.stats.cancelled == 0 and w1.serving.stats.failed == 0
    assert w2.serving.stats.migrated_in == n

    async def all_done():
        for _ in range(2):
            await bus.drain()
        for j in jobs:
            if await js.get_state(j) != "SUCCEEDED":
                return False
        return True

    await wait_until(all_done, timeout_s=60, msg="all jobs SUCCEEDED")
    for jid, prompt in jobs.items():
        oracle = fake_ref(prompt, 60)
        res = await ms.get_result(jid)
        assert res["tokens"] == oracle, jid
        assert tap.streams[jid] == oracle, jid  # no dup/missing tokens
        events = [e.get("event") for e in await js.events(jid)]
        assert "cancelled" not in events
    # the drained worker beacons draining=True and took no new work
    assert w1.build_heartbeat().draining is True
    await w2.stop(), await w1.stop(), await eng.stop(), await bus.close()


async def test_worker_death_fails_sessions_over_with_resume_prefix():
    """ISSUE 12 crash acceptance (in-process twin of the chaos test): kill
    a serving worker mid-decode with 3 active sessions — the scheduler's
    WorkerFailover re-dispatches each to the peer with the streamed tokens
    as a forced-decode prefix, and every client-visible stream assembles to
    exactly the oracle output."""
    from cordum_tpu.controlplane.scheduler.reconciler import WorkerFailover
    from cordum_tpu.protocol import subjects as subj
    from cordum_tpu.protocol.types import BusPacket, JobRequest

    from .test_batching import make_stack
    from .test_serving import settle

    kv, bus, js, ms, eng = make_stack()
    eng.registry.ttl_s = 1.0  # dead-worker detection window for the test
    await eng.start()
    w1 = make_serving_worker(bus, ms, "w-k1", step_delay=0.03)
    w2 = make_serving_worker(bus, ms, "w-k2", step_delay=0.005)
    await w1.start()
    await w2.start()
    tap = StreamTap()
    await bus.subscribe(subj.PROGRESS, tap)
    await settle(bus)
    # both workers heartbeat faster than the 1s registry TTL; w1's pump is
    # the thing the "SIGKILL" below silences
    hb1_task = asyncio.ensure_future(_heartbeat_pump(w1, 0.2))
    hb_task = asyncio.ensure_future(_heartbeat_pump(w2, 0.2))
    fo = WorkerFailover(eng, js, eng.registry,
                        Timeouts(scan_interval_s=0.2))
    await fo.start()
    n = 3
    jobs = {}
    for i in range(n):
        jid = f"kx{i}"
        prompt = [i + 2, 9, 4]
        jobs[jid] = prompt
        ptr = await ms.put_context(jid, {
            "op": "llm.generate", "tokens": prompt, "max_new_tokens": 80,
            "session_id": f"conv-kx{i}",
        })
        await bus.publish(subj.SUBMIT, BusPacket.wrap(JobRequest(
            job_id=jid, topic="job.tpu.generate", context_ptr=ptr,
            labels={"preferred_worker_id": "w-k1"}, tenant_id="default")))
    await wait_until(lambda: w1.serving.active_sessions() == n,
                     msg="sessions decoding on w1")
    await wait_until(
        lambda: all(len(tap.streams.get(j, [])) >= 4 for j in jobs),
        msg="streams flowing")
    hb1_task.cancel()
    await hard_kill(w1)  # SIGKILL semantics: silence, no cleanup

    async def all_done():
        for _ in range(2):
            await bus.drain()
        for j in jobs:
            if await js.get_state(j) != "SUCCEEDED":
                return False
        return True

    await wait_until(all_done, timeout_s=60, msg="sessions resumed on w-k2")
    for jid, prompt in jobs.items():
        oracle = fake_ref(prompt, 80)
        res = await ms.get_result(jid)
        assert res["tokens"] == oracle, jid
        # exactly-once client stream across the crash: the offset-assembled
        # sequence equals the oracle (the StreamTap also asserted the
        # replayed prefix agreed token-for-token)
        assert tap.streams[jid] == oracle, jid
        events = [e.get("event") for e in await js.events(jid)]
        assert "failover" in events, events
    assert eng.metrics.session_failovers.value(reason="worker_dead") >= n
    # the failed-over sessions really resumed mid-stream: w2 decoded fewer
    # tokens than the full oracle for at least one session
    assert w2.serving.stats.migrated_in == 0  # crash path ships no pages
    hb_task.cancel()
    await fo.stop()
    await w2.stop(), await eng.stop(), await bus.close()


async def _heartbeat_pump(worker, interval_s: float):
    while True:
        await asyncio.sleep(interval_s)
        try:
            await worker.send_heartbeat()
        except Exception:  # noqa: BLE001 - bus closing at teardown
            return


async def hard_kill(w):
    """SIGKILL semantics in-process: subscriptions vanish, the decode loop
    dies mid-step, and NOTHING is published — no cancels, no results, no
    final heartbeat (contrast Worker.stop / Worker.drain)."""
    for s in [*w._subs, *w._topic_subs]:
        s.unsubscribe()
    w._subs, w._topic_subs = [], []
    if w._hb_task:
        w._hb_task.cancel()
    if w._migration is not None:
        await w._migration.stop()
    eng = w._serving
    if eng is not None:
        eng._closed = True  # no restarts, no eviction publishes
        if eng._loop_task is not None:
            eng._loop_task.cancel()
        # let the dead worker's in-process coroutines unwind WITHOUT
        # publishing anything (SessionMigrated is the publish-nothing
        # path) — a real SIGKILL'd process just vanishes, but these tasks
        # share our event loop and would otherwise wedge bus.drain()
        for sess in [*eng._pending, *eng._active.values()]:
            if not sess.future.done():
                sess.future.set_exception(SessionMigrated(sess.job_id))
    w._executor.shutdown(wait=False)


async def test_drain_without_peers_requeues_and_recovers():
    """Satellite 2 end-to-end: a drain with NO migration target requeues
    its sessions (SESSION_REQUEUE, never CANCELLED); the scheduler fails
    them over, and once a worker joins, the replayer's nudge hands the job
    to it — the client's assembled stream is still exactly the oracle."""
    from cordum_tpu.controlplane.scheduler.reconciler import PendingReplayer
    from cordum_tpu.infra.jobstore import JobStore
    from cordum_tpu.protocol import subjects as subj
    from cordum_tpu.protocol.types import BusPacket, JobRequest

    from .test_batching import make_stack
    from .test_serving import settle

    kv, bus, js, ms, eng = make_stack()
    await eng.start()
    w1 = make_serving_worker(bus, ms, "w-rq1", step_delay=0.02)
    await w1.start()
    tap = StreamTap()
    await bus.subscribe(subj.PROGRESS, tap)
    await settle(bus)
    rep = PendingReplayer(eng, JobStore(kv), Timeouts(
        scan_interval_s=0.2, pending_replay_s=60.0, dispatch_timeout_s=60.0,
        result_replay_s=0.5))
    await rep.start()
    ptr = await ms.put_context("rq1", {
        "op": "llm.generate", "tokens": [5, 5], "max_new_tokens": 30,
        "session_id": "conv-rq",
    })
    await bus.publish(subj.SUBMIT, BusPacket.wrap(JobRequest(
        job_id="rq1", topic="job.tpu.generate", context_ptr=ptr,
        tenant_id="default")))
    await wait_until(lambda: len(tap.streams.get("rq1", [])) >= 3,
                     msg="stream flowing on w1")
    await w1.drain(timeout_s=10)  # fleet of one: nowhere to migrate
    assert w1.serving.stats.requeued == 1
    assert w1.serving.stats.cancelled == 0 and w1.serving.stats.failed == 0
    await settle(bus)
    assert await js.get_state("rq1") == "RUNNING"  # failed over, not killed
    # a replacement worker joins; the replayer's nudge hands the job over
    w2 = make_serving_worker(bus, ms, "w-rq2", step_delay=0.005)
    await w2.start()

    async def done():
        for _ in range(2):
            await bus.drain()
        return await js.get_state("rq1") == "SUCCEEDED"

    await wait_until(done, timeout_s=30, msg="job recovered on w2")
    oracle = fake_ref([5, 5], 30)
    assert (await ms.get_result("rq1"))["tokens"] == oracle
    # the fresh run replayed from offset 0; dedupe-by-offset keeps the
    # assembled client stream exactly-once
    assert tap.streams["rq1"] == oracle
    events = [e.get("event") for e in await js.events("rq1")]
    assert "failover" in events and "cancelled" not in events, events
    await rep.stop()
    await w2.stop(), await w1.stop(), await eng.stop(), await bus.close()


# --------------------------------------------------- gateway + sdk surface


class SlowServingGwStack:
    """Gateway + scheduler + a SLOW serving worker behind live HTTP — slow
    enough that a mid-stream replay injection has a real window."""

    def __init__(self):
        from .test_gateway import GwStack

        self.inner = GwStack()

    async def __aenter__(self):
        from cordum_tpu.controlplane.scheduler.strategy import LeastLoadedStrategy
        from cordum_tpu.infra.config import parse_pool_config

        s = self.inner
        pc = parse_pool_config({
            "topics": {"job.work": "p", "job.tpu.generate": "tpu"},
            "pools": {"p": {}, "tpu": {}},
        })
        s.scheduler.strategy = LeastLoadedStrategy(s.scheduler.registry, pc)
        await s.__aenter__()
        self.worker = make_serving_worker(s.bus, s.mem, "w-slow",
                                          step_delay=0.03)
        await self.worker.start()
        await s.settle()
        return self

    async def __aexit__(self, *exc):
        await self.worker.stop()
        await self.inner.__aexit__(*exc)


async def test_sdk_drain_endpoint_and_offset_dedupe():
    """`POST /api/v1/workers/{id}/drain` publishes the drain request, and
    the SDK stream iterator dedupes replayed offsets (an injected offset-0
    replay mid-stream — what a failed-over worker emits — must not
    duplicate client tokens)."""
    from cordum_tpu.protocol import subjects as subj
    from cordum_tpu.protocol.types import (
        BusPacket, JobProgress, STATUS_HINT_STREAM,
    )
    from cordum_tpu.sdk.client import Client

    async with SlowServingGwStack() as st:
        s = st.inner
        drains = []

        async def drain_tap(subject, pkt):
            if pkt.worker_drain is not None:
                drains.append(pkt.worker_drain.worker_id)

        await s.bus.subscribe(subj.DRAIN, drain_tap)
        oracle = fake_ref([1, 2, 3], 20)
        injected = asyncio.Event()

        async def progress_tap(subject, pkt):
            # after the 2nd real token, replay the first two at offset 0 —
            # exactly the duplicate a failover catch-up packet produces
            pr = pkt.job_progress
            if (
                pr is not None and pr.status_hint == STATUS_HINT_STREAM
                and pr.worker_id == "w-slow" and not injected.is_set()
                and pr.offset + len(pr.tokens) >= 2
            ):
                injected.set()
                await s.bus.publish(subj.PROGRESS, BusPacket.wrap(JobProgress(
                    job_id=pr.job_id, status_hint=STATUS_HINT_STREAM,
                    worker_id="fake-replayer", tokens=list(oracle[:2]),
                    offset=0,
                )))

        await s.bus.subscribe(subj.PROGRESS, progress_tap)
        c = Client(str(s.client.make_url("")), api_key="user-key")
        try:
            doc = await c.drain_worker("some-worker", reason="test")
            assert doc["draining"] is True
            await s.settle()
            assert drains == ["some-worker"]
            got = [t async for t in c.generate(
                [1, 2, 3], session_id="conv-dedupe", max_new_tokens=20,
                timeout_s=60)]
            assert injected.is_set(), "replay was never injected"
            assert got == oracle  # replay deduped, nothing duplicated
        finally:
            await c.close()
