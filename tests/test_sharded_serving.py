"""Sharded serving gangs (docs/SERVING.md §Sharded serving): per-rank
KV-page record slicing/merging (byte-identity property), TP=2 gang token
streams bit-identical to the single-rank fp32 oracle (greedy and
speculative, exactly ONE compiled ragged program per rank), drain with a
gang member as migration source, the statebus-backed cold tier surviving
a worker restart, gang-aware capacity fusing + placement routing, and
the serving-gang e2e over the live gang scheduler stack."""
import asyncio
import random
import time

import numpy as np
import pytest

from cordum_tpu.serving.engine import GenRequest, ServingEngine, SessionMigrated
from cordum_tpu.serving.pager import PageAllocator
from cordum_tpu.serving.shard import (
    ServingGangGroup,
    ShardedServingBackend,
    entry_from_wire,
    entry_to_wire,
    heads_for_rank,
    merge_rank_records,
    slice_rank_record,
)

from .test_serving import ref_greedy, run_blocking
from .test_serving_failover import install_into, wait_until


def tiny_cfg():
    import jax.numpy as jnp

    from cordum_tpu.models import llama

    return llama.LlamaConfig(vocab_size=256, d_model=64, n_layers=2,
                             n_heads=4, n_kv_heads=2, d_ff=128,
                             max_seq_len=128, dtype=jnp.float32)


def tiny_params(cfg):
    import jax

    from cordum_tpu.models import llama

    return llama.init_params(jax.random.PRNGKey(0), cfg)


# ---------------------------------------------------------------------------
# per-rank record format
# ---------------------------------------------------------------------------


def test_heads_for_rank_split():
    assert [heads_for_rank(8, 4, r) for r in range(4)] == [
        (0, 2), (2, 4), (4, 6), (6, 8)]
    assert heads_for_rank(2, 1, 0) == (0, 2)
    with pytest.raises(ValueError):
        heads_for_rank(6, 4, 0)  # not divisible
    with pytest.raises(ValueError):
        heads_for_rank(8, 4, 4)  # rank outside tp


def test_rank_record_slice_merge_roundtrip_property():
    """Any page record sliced per rank and merged back — in any rank
    order, alongside plain records — is BYTE-identical to the original;
    missing or overlapping slices are refused."""
    rng = random.Random(20_06)
    for _ in range(25):
        layers = rng.choice([1, 2, 3])
        used = rng.randint(1, 16)
        kvh = rng.choice([2, 4, 8])
        hd = rng.choice([4, 16])
        tp = rng.choice([t for t in (2, 4, 8) if kvh % t == 0])
        data = np.arange(layers * used * kvh * hd, dtype=np.float32)
        k = (data * 1.5).reshape(layers, used, kvh, hd)
        v = (data - 7.0).reshape(layers, used, kvh, hd)
        rec = {"i": rng.randint(0, 63), "used": used,
               "k": k.tobytes(), "v": v.tobytes(),
               "shape": [layers, used, kvh, hd]}
        slices = [
            slice_rank_record(rec, r, tp, *heads_for_rank(kvh, tp, r))
            for r in range(tp)
        ]
        rng.shuffle(slices)
        plain = {"i": rec["i"] + 64, "used": used, "k": k.tobytes(),
                 "v": v.tobytes(), "shape": [layers, used, kvh, hd]}
        merged = merge_rank_records([plain, *slices])
        assert [m["i"] for m in merged] == sorted([rec["i"], plain["i"]])
        got = next(m for m in merged if m["i"] == rec["i"])
        assert got["k"] == rec["k"] and got["v"] == rec["v"]
        assert got["shape"] == rec["shape"] and got["used"] == used
        if tp > 1:
            with pytest.raises(ValueError):
                merge_rank_records(slices[:-1])  # a rank went missing
            with pytest.raises(ValueError):
                merge_rank_records([*slices, slices[0]])  # overlap


def test_step_entry_wire_codec_roundtrip():
    from cordum_tpu.serving.backend import StepEntry

    e = StepEntry(tokens=[5, 9], start=12, pages=[3, 4], sample=False,
                  phase="prefill", key="s-1", draft=2)
    w = entry_to_wire(e)
    assert all(isinstance(v, (int, bool, str, list)) for v in w.values())
    back = entry_from_wire(w)
    assert (back.tokens, back.start, back.pages, back.sample,
            back.phase, back.key, back.draft) == (
        e.tokens, e.start, e.pages, e.sample, e.phase, e.key, e.draft)


# ---------------------------------------------------------------------------
# TP gang vs single-rank identity (backend level)
# ---------------------------------------------------------------------------


def drive_backend(be, prompt, n_new, reserve=0):
    """prefill + n_new-1 decode steps through the backend's compat
    conveniences, returning (tokens, pages, final_pos).  ``reserve``
    leaves page room for tokens the caller will decode afterwards."""
    alloc = PageAllocator(be.num_pages, be.page_size)
    pages = alloc.alloc("s0", alloc.pages_for(len(prompt) + n_new + reserve))
    first = be.prefill(prompt, pages)
    out, pos, last = [first], len(prompt), first
    for _ in range(n_new - 1):
        (nxt,) = be.decode([(last, pos, pages)])
        pos, last = pos + 1, int(nxt)
        out.append(last)
    # pos is where out[-1] gets written by the NEXT decode — KV holds
    # positions [0, pos) and a continuation feeds (out[-1], pos, pages)
    return out, pages, pos


def test_gang_export_matches_single_rank_and_reimports():
    """A TP=2 gang driven lock-step produces the SAME tokens as a single
    rank; its per-rank export merges byte-identical to the single-rank
    export; and the gang export imports into a fresh single-rank backend
    that then continues decoding identically — drain/failover/hand-off
    interop by construction."""
    cfg = tiny_cfg()
    params = tiny_params(cfg)
    single = type("_B", (object,), {})  # placeholder to appease linters
    single = __import__(
        "cordum_tpu.serving.backend", fromlist=["LlamaServingBackend"]
    ).LlamaServingBackend(cfg, num_pages=32, page_size=8,
                          params_provider=lambda: params)
    gang = ServingGangGroup(cfg, tp=2, num_pages=32, page_size=8,
                            params_provider=lambda: params)
    prompt = [7, 3, 11, 19, 2, 5, 23, 1, 13]
    n_new = 7
    toks_single, pages_s, end_s = drive_backend(single, prompt, n_new, reserve=5)
    toks_gang, pages_g, end_g = drive_backend(gang, prompt, n_new, reserve=5)
    assert toks_gang == toks_single == ref_greedy(cfg, params, prompt, n_new)
    assert gang.compiled_per_rank() == [1, 1]

    exp_single = single.export_kv(pages_s, 0, end_s)
    exp_gang = gang.export_kv(pages_g, 0, end_g)
    assert len(exp_gang) == 2 * len(exp_single)
    assert all(r["heads"] in ([0, 1], [1, 2]) for r in exp_gang)
    merged = merge_rank_records(exp_gang)
    assert len(merged) == len(exp_single)
    for m, s in zip(merged, exp_single):
        assert (m["i"], m["used"], m["shape"]) == (s["i"], s["used"], s["shape"])
        # the partitioned matmul's accumulation tiling differs from the
        # single-device program by at most the last ulp — token argmax is
        # what must match exactly (asserted above), arena floats to fp32 eps
        for fld in ("k", "v"):
            a = np.frombuffer(m[fld], dtype=np.float32)
            b = np.frombuffer(s[fld], dtype=np.float32)
            np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-6)

    # fresh single-rank backend adopts the RAW per-rank gang export (its
    # base import_kv merges) and continues where the gang stopped
    fresh = __import__(
        "cordum_tpu.serving.backend", fromlist=["LlamaServingBackend"]
    ).LlamaServingBackend(cfg, num_pages=32, page_size=8,
                          params_provider=lambda: params)
    alloc = PageAllocator(fresh.num_pages, fresh.page_size)
    pages_f = alloc.alloc("s0", len(pages_g))
    fresh.import_kv(pages_f, exp_gang)
    # the satellite's byte-identity bar: the TP=2 session exported
    # rank-by-rank and re-imported exports BYTE-identical to the merged
    # single-rank record set — pure data movement, no recompute
    re_exp = fresh.export_kv(pages_f, 0, end_g)
    assert len(re_exp) == len(merged)
    for r, m in zip(re_exp, merged):
        assert (r["used"], r["shape"]) == (m["used"], m["shape"])
        assert r["k"] == m["k"] and r["v"] == m["v"]
    last = toks_gang[-1]
    cont_fresh, cont_gang, cont_single = [], [], []
    pos_f = pos_g = pos_s = end_g
    lf = lg = ls = last
    for _ in range(5):
        (nf,) = fresh.decode([(lf, pos_f, pages_f)])
        (ng,) = gang.decode([(lg, pos_g, pages_g)])
        (ns,) = single.decode([(ls, pos_s, pages_s)])
        cont_fresh.append(int(nf))
        cont_gang.append(int(ng))
        cont_single.append(int(ns))
        lf, lg, ls = int(nf), int(ng), int(ns)
        pos_f, pos_g, pos_s = pos_f + 1, pos_g + 1, pos_s + 1
    # the importer is indistinguishable from the gang it adopted the
    # session from — THE hand-off/drain invariant.  (The gang's arena sits
    # an ulp from the single-device one, so deep continuations may flip a
    # near-tie argmax vs the from-scratch oracle; the single-rank backend
    # itself stays the oracle's bit-exact twin.)
    assert cont_fresh == cont_gang
    assert cont_single == ref_greedy(cfg, params, prompt + toks_single, 5)


def test_follower_rank_skips_sampling():
    """A follower compiles with sample_logits=False: step results are the
    zero buffer (lm_head dead-code-eliminated) while its arena writes stay
    identical — proven by its export matching the sampling rank's slice."""
    cfg = tiny_cfg()
    params = tiny_params(cfg)
    lead = ShardedServingBackend(cfg, rank=0, tp=2, num_pages=16, page_size=8,
                                 params_provider=lambda: params)
    follow = ShardedServingBackend(cfg, rank=1, tp=2, num_pages=16,
                                   page_size=8, params_provider=lambda: params)
    assert lead.sample_output and not follow.sample_output
    from cordum_tpu.serving.backend import StepEntry

    prompt = [9, 2, 7, 4]
    pages = [1, 2]
    entry = StepEntry(tokens=prompt, start=0, pages=pages, sample=True,
                      phase="prefill")
    (tok,) = lead.step([entry])
    (zero,) = follow.step([entry])
    assert int(tok) == ref_greedy(cfg, params, prompt, 1)[0]
    assert int(zero) == 0  # the DCE'd program returns the zero buffer
    lo, hi = lead.heads
    assert merge_rank_records(
        lead.export_kv(pages, 0, 4) + follow.export_kv(pages, 0, 4)
    )[0]["shape"][2] == cfg.n_kv_heads


# ---------------------------------------------------------------------------
# TP gang under the real engine: greedy + speculative oracle, compile count
# ---------------------------------------------------------------------------


async def test_tp2_engine_greedy_oracle_one_program_per_rank():
    cfg = tiny_cfg()
    params = tiny_params(cfg)
    gang = ServingGangGroup(cfg, tp=2, num_pages=32, page_size=8,
                            params_provider=lambda: params)
    eng = ServingEngine(gang, run_blocking=run_blocking,
                        max_new_tokens_cap=32, prefix_cache=False)
    prompts = {
        "g1": [7, 3, 11, 19, 2, 5, 23, 1, 13],
        "g2": [42, 9, 77, 5, 31],
    }
    subs = {
        jid: asyncio.ensure_future(eng.submit(
            GenRequest(prompt=p, max_new_tokens=8, stream=False), job_id=jid))
        for jid, p in prompts.items()
    }
    for jid, p in prompts.items():
        out = await asyncio.wait_for(subs[jid], timeout=180)
        assert out["tokens"] == ref_greedy(cfg, params, p, 8)
    # the acceptance bar: exactly ONE compiled ragged program per rank —
    # prefill chunks, mixed batches and decode all rode the same shapes
    assert gang.compiled_per_rank() == [1, 1]
    await eng.stop()


async def test_tp2_engine_speculative_oracle():
    """Speculative decoding over the gang: draft rows ride the same ragged
    program on every rank (followers replay identical entries), and the
    accepted stream is STILL bit-identical to the fp32 oracle."""
    cfg = tiny_cfg()
    params = tiny_params(cfg)
    gang = ServingGangGroup(cfg, tp=2, num_pages=32, page_size=8,
                            params_provider=lambda: params)
    eng = ServingEngine(gang, run_blocking=run_blocking,
                        max_new_tokens_cap=32, prefix_cache=False,
                        speculative=True, draft_k=4)
    # a repetitive prompt gives the n-gram drafter something to accept
    prompt = [5, 9, 5, 9, 5, 9, 5, 9, 5, 9]
    out = await asyncio.wait_for(eng.submit(
        GenRequest(prompt=prompt, max_new_tokens=10, stream=False),
        job_id="sp1"), timeout=180)
    assert out["tokens"] == ref_greedy(cfg, params, prompt, 10)
    assert gang.compiled_per_rank() == [1, 1]
    await eng.stop()


async def test_drain_with_gang_member_source_token_identical():
    """A session decoding on a TP=2 gang live-migrates to a SINGLE-rank
    peer mid-decode (the drain path with a gang as source): per-rank
    records ship on the wire, the receiver's base import merges them, and
    the finished stream equals the never-migrated oracle."""
    from cordum_tpu.serving.backend import LlamaServingBackend
    from cordum_tpu.serving.migration import MigrationServer, migrate_session

    cfg = tiny_cfg()
    params = tiny_params(cfg)
    gang = ServingGangGroup(cfg, tp=2, num_pages=32, page_size=8,
                            params_provider=lambda: params)
    a = ServingEngine(gang, run_blocking=run_blocking, max_new_tokens_cap=64,
                      prefix_cache=False)
    be_b = LlamaServingBackend(cfg, num_pages=32, page_size=8,
                               params_provider=lambda: params)
    b = ServingEngine(be_b, run_blocking=run_blocking, max_new_tokens_cap=64)
    results: dict = {}
    srv = MigrationServer(install_into(b, results))
    await srv.start()
    prompt = [7, 3, 11, 19, 2, 5, 23, 1, 13]
    src = asyncio.ensure_future(a.submit(
        GenRequest(prompt=prompt, max_new_tokens=20, stream=False),
        job_id="gm1"))
    await wait_until(
        lambda: (a.export_state("gm1") or {}).get("pos", 0) >= 12,
        timeout_s=180, msg="gang session mid-decode")
    assert await migrate_session(a, "gm1", srv.host, srv.port) is True
    with pytest.raises(SessionMigrated):
        await asyncio.wait_for(src, timeout=10)
    await wait_until(lambda: "gm1" in results, timeout_s=180,
                     msg="single-rank peer finished")
    assert results["gm1"] == ref_greedy(cfg, params, prompt, 20)
    assert a.allocator.used_pages == 0
    await a.stop(), await b.stop(), await srv.stop()


# ---------------------------------------------------------------------------
# statebus-backed cold tier: hibernated sessions survive a restart
# ---------------------------------------------------------------------------


async def test_statebus_cold_tier_restores_after_restart(kv):
    """serving_cold_tier=statebus: a session hibernated on worker
    generation 1 is journaled through the statebus KV; generation 2 (fresh
    engine, empty RAM) loads the journal and restores it token-identically.
    The restore consumes the journal entry."""
    from cordum_tpu.serving.tiering import StatebusColdTier

    from .test_prefix_tiering import ArenaFakeBackend, arena_ref

    def mk_engine():
        be = ArenaFakeBackend(num_pages=32, page_size=4, max_context=128,
                              step_delay=0.01)
        eng = ServingEngine(be, run_blocking=run_blocking,
                            max_new_tokens_cap=64)
        eng.tiering.arena = StatebusColdTier(kv, worker_id="w0")
        return eng

    eng1 = mk_engine()
    prompt = [3, 1, 4, 1, 5]
    src = asyncio.ensure_future(eng1.submit(
        GenRequest(prompt=prompt, max_new_tokens=24, stream=False,
                   session_key="hib"),
        job_id="h1"))
    await wait_until(
        lambda: (eng1.export_state("h1") or {}).get("pos", 0) >= 10,
        msg="session mid-decode")
    assert await eng1.hibernate_session("h1") is True
    with pytest.raises(Exception):
        await asyncio.wait_for(src, timeout=5)
    await eng1.tiering.arena.flush()
    assert await kv.keys("serving:cold:w0:") == ["serving:cold:w0:h1"]
    await eng1.stop()  # the "crash": RAM mirror dies with the process

    eng2 = mk_engine()
    assert "h1" not in eng2.tiering.arena
    assert await eng2.tiering.arena.load() == 1
    assert "h1" in eng2.tiering.arena
    fut = await eng2.restore_hibernated("h1")
    toks = await asyncio.wait_for(fut, timeout=20)
    assert toks == arena_ref(prompt, 24)
    await eng2.tiering.arena.flush()
    assert await kv.keys("serving:cold:w0:") == []  # journal consumed
    await eng2.stop()


def test_cold_tier_config_knob():
    from cordum_tpu.infra.config import parse_pool_config
    from cordum_tpu.infra.configschema import ConfigError

    pc = parse_pool_config(
        {"pools": {"tpu": {"serving_cold_tier": "statebus"}}})
    assert pc.pools["tpu"].serving_cold_tier == "statebus"
    assert parse_pool_config({"pools": {"tpu": {}}}) \
        .pools["tpu"].serving_cold_tier == ""
    with pytest.raises(ConfigError, match="serving_cold_tier"):
        parse_pool_config({"pools": {"tpu": {"serving_cold_tier": "redis"}}})


def test_bench_floor_gates_tp_keys():
    """bench_floor.json carries the ISSUE 20 contracts: token identity and
    one-program-per-rank are exact, tp_speedup is the 1-core-host collapse
    guard — and a MISSING tp key is itself a violation."""
    import json as _json
    import sys as _sys
    from pathlib import Path

    repo = Path(__file__).resolve().parents[1]
    _sys.path.insert(0, str(repo / "tools"))
    try:
        import check_bench_floor as mod
    finally:
        _sys.path.pop(0)
    floors = _json.loads((repo / "bench_floor.json").read_text())
    base = {"tp_token_identity": 1, "tp_speedup": 0.51,
            "tp_tokens_per_sec": 15.5, "tp_compile_per_rank": 1}
    assert not any("tp_" in v for v in mod.check(dict(base), floors))
    for key, bad in [("tp_token_identity", 0), ("tp_speedup", 0.1),
                     ("tp_tokens_per_sec", 0.0), ("tp_compile_per_rank", 2)]:
        doc = dict(base)
        doc[key] = bad
        assert any(key in v for v in mod.check(doc, floors)), key
    doc = dict(base)
    doc.pop("tp_token_identity")
    assert any("tp_token_identity" in v for v in mod.check(doc, floors))


# ---------------------------------------------------------------------------
# gang-aware capacity fusing + placement
# ---------------------------------------------------------------------------


def _gang_beacon(instance, *, gang="g1", rank, size=2, members=("wa", "wb"),
                 pages_total=64, pages_free=40, tokens_per_s=0.0, seq=0):
    from cordum_tpu.protocol.types import TelemetrySnapshot

    sg = {"gang_id": gang, "rank": rank, "size": size,
          "members": list(members), "pages_total": pages_total,
          "pages_free": pages_free}
    if rank == 0:
        sg["tokens_per_s"] = tokens_per_s
    block = {"v": 1, "seq": seq, "full": True, "device_kind": "cpu",
             "rows": {}, "serving_gang": sg}
    return TelemetrySnapshot(service="worker", instance=instance, seq=seq,
                             started_at_us=1, interval_s=2.0,
                             health={"role": "worker", "capacity": block})


def test_capacity_view_fuses_serving_gang_rows():
    """One fused row per gang: leader's measured tokens/s, min-of-ranks
    page headroom, members by rank; a beacon without the block clears the
    worker's membership."""
    from cordum_tpu.obs.capacity import CapacityView

    clock = [0.0]
    view = CapacityView(clock=lambda: clock[0])
    view.ingest(_gang_beacon("wa", rank=0, pages_free=40, tokens_per_s=321.5))
    view.ingest(_gang_beacon("wb", rank=1, pages_free=12))
    gangs = view.serving_gangs()
    assert set(gangs) == {"g1"}
    g = gangs["g1"]
    assert g["leader"] == "wa" and g["members"] == {"wa": 0, "wb": 1}
    assert g["tokens_per_s"] == 321.5
    assert g["pages_free_min"] == 12 and g["pages_total_min"] == 64
    assert view.serving_gang("wb")["rank"] == 1
    # the follower's next beacon drops the block: membership clears
    from cordum_tpu.protocol.types import TelemetrySnapshot

    view.ingest(TelemetrySnapshot(
        service="worker", instance="wb", seq=1, started_at_us=1,
        interval_s=2.0,
        health={"role": "worker",
                "capacity": {"v": 1, "seq": 1, "full": True,
                             "device_kind": "cpu", "rows": {}}}))
    assert view.serving_gang("wb") == {}
    assert view.serving_gangs()["g1"]["members"] == {"wa": 0}


def test_placer_excludes_followers_and_routes_to_faster_gang():
    """2-gang skew: follower ranks never take new sessions; the two
    leaders split placements in proportion to their gangs' fused measured
    step throughput (the acceptance-bar routing test)."""
    from cordum_tpu.controlplane.scheduler.placer import ServingPlacer

    from .test_disagg import StubView, hb

    class GangView(StubView):
        def __init__(self):
            super().__init__()
            self.gangs: dict[str, dict] = {}

        def serving_gangs(self):
            return {k: dict(v) for k, v in self.gangs.items()}

    view = GangView()
    for w in ("wa0", "wa1", "wb0", "wb1"):
        view.kv[w] = {"pages_total": 64, "pages_free": 64}
    view.gangs["ga"] = {
        "gang_id": "ga", "size": 2, "leader": "wa0",
        "members": {"wa0": 0, "wa1": 1}, "tokens_per_s": 300.0,
        "pages_free_min": 60, "pages_total_min": 64,
    }
    view.gangs["gb"] = {
        "gang_id": "gb", "size": 2, "leader": "wb0",
        "members": {"wb0": 0, "wb1": 1}, "tokens_per_s": 100.0,
        "pages_free_min": 60, "pages_total_min": 64,
    }
    placer = ServingPlacer(view)
    cands = [hb(w) for w in ("wa0", "wa1", "wb0", "wb1")]
    picks = {w: 0 for w in ("wa0", "wa1", "wb0", "wb1")}
    for _ in range(120):
        picks[placer.pick(cands)] += 1
    assert picks["wa1"] == picks["wb1"] == 0  # followers excluded outright
    assert picks["wa0"] + picks["wb0"] == 120
    assert picks["wa0"] >= 2 * picks["wb0"] > 0  # 3:1 fused-rate skew
    # min-of-ranks headroom gates the gang: the slow gang's tightest rank
    # filling up starves it entirely
    view.gangs["gb"]["pages_free_min"] = 0
    placer2 = ServingPlacer(view)
    assert all(placer2.pick(cands) == "wa0" for _ in range(10))


def test_serving_gang_renders():
    from cordum_tpu.controlplane.scheduler.gang import render_gang_table
    from cordum_tpu.obs.capacity import render_capacity_table

    cap = render_capacity_table({
        "workers": [], "totals": {},
        "serving_gangs": [{
            "gang_id": "g-1", "size": 2, "leader": "wa",
            "members": {"wa": 0, "wb": 1}, "tokens_per_s": 123.4,
            "pages_free_min": 12, "pages_total_min": 64,
        }],
    })
    assert "serving gangs" in cap and "wa:0" in cap and "wb:1" in cap
    assert "123.4" in cap
    tbl = render_gang_table({"gangs": [
        {"gang_id": "g-1", "job_id": "j-1", "state": "RUNNING",
         "kind": "serving", "workers": 2, "ready": 2, "done": 0,
         "age_s": 3.0, "members": ["wa", "wb"]},
        {"gang_id": "g-2", "job_id": "j-2", "state": "DONE",
         "workers": 2, "ready": 2, "done": 2, "age_s": 9.0,
         "members": ["wc", "wd"]},
    ]})
    assert "KIND" in tbl and "serving" in tbl
    assert "spmd" in tbl  # unkinded gangs render the SPMD default


# ---------------------------------------------------------------------------
# serving-gang e2e over the live gang-scheduler stack
# ---------------------------------------------------------------------------


async def test_serving_gang_e2e_token_identical_rank0_streams():
    """A 2-member serving gang over the real stack: all-or-nothing
    reservation, rendezvous, leader engine + follower replay, ONE terminal
    result whose tokens equal the fp32 oracle, rank-0-only stream packets,
    kind=serving in the gangs doc, and a clean ledger after."""
    from cordum_tpu.protocol import subjects as subj
    from cordum_tpu.protocol.types import (
        LABEL_GANG_KIND,
        LABEL_GANG_WORKERS,
        BusPacket,
        JobRequest,
        STATUS_HINT_STREAM,
    )

    from .test_gang import make_stack, teardown, wait_state

    stack = await make_stack(2, peer_timeout_s=60.0)
    stream_senders = set()

    async def tap(subject, pkt):
        p = pkt.job_progress
        if p is not None and p.status_hint == STATUS_HINT_STREAM:
            stream_senders.add(p.worker_id)

    await stack.bus.subscribe(subj.PROGRESS, tap)
    cfg = tiny_cfg()
    try:
        prompt = [7, 3, 11, 19, 2, 5, 23, 1, 13]
        payload = {"op": "llm.generate",
                   "gang": {"kind": "serving", "workers": 2},
                   "prompts": [prompt], "max_new_tokens": 6,
                   "page_size": 8, "cache_pages": 32}
        ptr = await stack.store.put_context("g-serve", payload)
        req = JobRequest(
            job_id="g-serve", topic="job.gang", tenant_id="default",
            context_ptr=ptr,
            labels={LABEL_GANG_WORKERS: "2", LABEL_GANG_KIND: "serving"},
        )
        await stack.bus.publish(subj.SUBMIT,
                                BusPacket.wrap(req, sender_id="test"))
        assert await wait_state(stack.js, "g-serve", timeout_s=240) == "SUCCEEDED"
        res = await stack.store.get_result("g-serve")
        assert res["kind"] == "serving" and res["mode"] == "serving"
        lead = res["per_rank"]["0"]
        follow = res["per_rank"]["1"]
        # the gang runner builds its model from the payload seed (0) with
        # LlamaConfig.tiny() — the oracle uses the same derivation
        import dataclasses

        import jax
        import jax.numpy as jnp

        from cordum_tpu.models import llama

        ecfg = dataclasses.replace(llama.LlamaConfig.tiny(),
                                   dtype=jnp.float32)
        params = llama.init_params(jax.random.PRNGKey(0), ecfg)
        assert lead["results"][0]["tokens"] == ref_greedy(
            ecfg, params, prompt, 6)
        # one compiled ragged program per rank; the follower replayed every
        # broadcast step and sampled nothing
        assert lead["compiled"] == 1 and follow["compiled"] == 1
        assert follow["steps_replayed"] == lead["steps"] > 0
        assert res["sessions"] == 1 and res["tokens"] == 6
        # rank 0 alone streamed
        assert len(stream_senders) == 1
        # observability: the live doc carried kind=serving while running —
        # the finished record keeps it
        gdoc = stack.gangs.doc()
        assert any(g["kind"] == "serving" for g in gdoc)
        assert stack.gangs.ledger.reserved_workers == {}
        assert stack.gangs.ledger.verify() == 0
        m = stack.eng.metrics
        assert m.serving_gang_steps.value(role="lead") > 0
        assert m.serving_gang_steps.value(role="replay") > 0
    finally:
        await teardown(stack)
