"""Keyspace-sharded control plane (ISSUE 5): partition ownership, shard
equivalence, degraded mode, the partitioned statebus client, and the
coalesced wire path."""
from __future__ import annotations

import asyncio
import subprocess
import sys

import pytest

from cordum_tpu.controlplane.safetykernel.kernel import SafetyKernel
from cordum_tpu.controlplane.scheduler.engine import Engine
from cordum_tpu.controlplane.scheduler.safety_client import SafetyClient
from cordum_tpu.controlplane.scheduler.strategy import LeastLoadedStrategy
from cordum_tpu.infra.bus import LoopbackBus
from cordum_tpu.infra.config import parse_pool_config
from cordum_tpu.infra.jobstore import JobStore
from cordum_tpu.infra.kv import MemoryKV
from cordum_tpu.infra.registry import WorkerRegistry
from cordum_tpu.infra.statebus import (
    PartitionedBus,
    PartitionedKV,
    StateBusServer,
    connect_partitioned,
)
from cordum_tpu.protocol import subjects as subj
from cordum_tpu.protocol.jobhash import job_hash
from cordum_tpu.protocol.partition import owns, partition_of
from cordum_tpu.protocol.types import (
    BusPacket,
    Heartbeat,
    JobRequest,
    JobResult,
    JobState,
    LABEL_PARTITION,
)
from cordum_tpu.worker.runtime import Worker


# ---------------------------------------------------------------------------
# partition function
# ---------------------------------------------------------------------------


def test_partition_of_golden_values():
    """Frozen expectations: a change here re-shuffles ownership of every
    in-flight job across a rolling restart — never change silently."""
    assert partition_of("job-0001", 2) == 0
    assert partition_of("job-0002", 4) == 0
    assert partition_of("alpha", 4) == 2
    assert partition_of("bravo", 8) == 1
    assert partition_of("charlie", 8) == 6


def test_partition_of_unsharded_is_zero():
    assert partition_of("anything", 1) == 0
    assert partition_of("anything", 0) == 0


def test_partition_of_stable_across_processes():
    ids = ["job-0001", "alpha", "bravo", "charlie", "x" * 64]
    script = (
        "from cordum_tpu.protocol.partition import partition_of\n"
        f"print([partition_of(i, 8) for i in {ids!r}])\n"
    )
    out = subprocess.run([sys.executable, "-c", script], capture_output=True,
                         text=True, timeout=60, check=True)
    assert eval(out.stdout.strip()) == [partition_of(i, 8) for i in ids]


def test_every_job_routes_to_exactly_one_shard():
    for n in (2, 3, 4, 8):
        for i in range(200):
            jid = f"job-{i:04d}"
            owners = [s for s in range(n) if owns(jid, s, n)]
            assert len(owners) == 1
            assert owners[0] == partition_of(jid, n)


def test_partition_spread_is_reasonable():
    counts = [0] * 4
    for i in range(2000):
        counts[partition_of(f"job-{i}", 4)] += 1
    assert min(counts) > 2000 / 4 * 0.7  # no pathological skew


# ---------------------------------------------------------------------------
# subjects + labels
# ---------------------------------------------------------------------------


def test_partitioned_subjects():
    assert subj.submit_subject(0, 1) == subj.SUBMIT
    assert subj.submit_subject(2, 4) == "sys.job.submit.2"
    assert subj.result_subject(1, 2) == "sys.job.result.1"
    assert subj.cancel_subject(3, 4) == "sys.job.cancel.3"
    assert subj.submit_subject_for("alpha", 4) == "sys.job.submit.2"
    assert subj.submit_subject_for("alpha", 1) == subj.SUBMIT
    assert subj.stamped_result_subject("3") == "sys.job.result.3"
    assert subj.stamped_result_subject("") == subj.RESULT
    for s in ("sys.job.submit.2", "sys.job.result.0", "sys.job.cancel.7"):
        assert subj.is_durable_subject(s), s


def test_job_hash_ignores_partition_stamp():
    a = JobRequest(job_id="j1", topic="job.x")
    b = JobRequest(job_id="j1", topic="job.x", labels={LABEL_PARTITION: "3"})
    assert job_hash(a) == job_hash(b)


def test_worker_result_subject_echoes_partition():
    stamped = JobRequest(job_id="j", topic="t", labels={LABEL_PARTITION: "2"})
    plain = JobRequest(job_id="j", topic="t")
    assert Worker._result_subject(stamped) == "sys.job.result.2"
    assert Worker._result_subject(plain) == subj.RESULT


# ---------------------------------------------------------------------------
# sharded engine cluster helpers
# ---------------------------------------------------------------------------


async def _all_succeeded(js: JobStore, jobs: list) -> bool:
    for j in jobs:
        if await js.get_state(j) != "SUCCEEDED":
            return False
    return True


def _mk_engine(bus, kv, *, index: int, count: int) -> Engine:
    kernel = SafetyKernel(
        policy_doc={"tenants": {"default": {"allow_topics": ["job.*", "job.>"]}}}
    )
    reg = WorkerRegistry()
    pc = parse_pool_config(
        {"topics": {"job.bench": "bench"}, "pools": {"bench": {"requires": []}}}
    )
    eng = Engine(
        bus=bus, job_store=JobStore(kv), safety=SafetyClient(kernel.check),
        strategy=LeastLoadedStrategy(reg, pc), registry=reg,
        instance_id=f"shard-{index}", shard_index=index, shard_count=count,
    )
    reg.update(Heartbeat(worker_id="w1", pool="bench", max_parallel_jobs=1 << 30))
    return eng


async def _attach_worker(bus):
    async def worker_handler(subject, pkt):
        req = pkt.job_request
        await bus.publish(
            subj.stamped_result_subject((req.labels or {}).get(LABEL_PARTITION, "")),
            BusPacket.wrap(
                JobResult(job_id=req.job_id, status="SUCCEEDED", worker_id="w1"),
                sender_id="w1",
            ),
        )

    await bus.subscribe(subj.direct_subject("w1"), worker_handler, queue="w")


async def _run_cluster(shard_count: int, job_ids: list[str], *, stamped: bool = True):
    """Run a full submit→result pass over `shard_count` engine shards on one
    loopback bus + shared KV; returns {job_id: (state, [event names])}."""
    kv = MemoryKV()
    bus = LoopbackBus()
    engines = [_mk_engine(bus, kv, index=i, count=shard_count) for i in range(shard_count)]
    for eng in engines:
        await eng.start()
    await _attach_worker(bus)
    for jid in job_ids:
        subject = (subj.submit_subject_for(jid, shard_count) if stamped else subj.SUBMIT)
        await bus.publish(
            subject,
            BusPacket.wrap(JobRequest(job_id=jid, topic="job.bench",
                                      tenant_id="default"), sender_id="t"),
        )
    js = JobStore(kv)
    for _ in range(2000):
        await bus.drain()
        states = [await js.get_state(j) for j in job_ids]
        if all(s == "SUCCEEDED" for s in states):
            break
        await asyncio.sleep(0.005)
    out = {}
    for jid in job_ids:
        events = [e["event"] for e in await js.events(jid)]
        out[jid] = (await js.get_state(jid), events)
    for eng in engines:
        await eng.stop()
    await bus.close()
    return out, engines


async def test_two_shard_run_matches_single_shard():
    """Satellite: a 2-shard engine run lands the same final states and
    event logs as a 1-shard run over the same submit set."""
    jobs = [f"eq-{i}" for i in range(24)]
    single, _ = await _run_cluster(1, jobs)
    double, engines = await _run_cluster(2, jobs)
    assert single == double
    assert all(s == "SUCCEEDED" for s, _ in double.values())
    # both shards actually scheduled work (ownership split, no cross-locks)
    per_shard = [e.metrics.shard_scheduled.value(shard=str(e.shard_index)) for e in engines]
    assert all(v > 0 for v in per_shard), per_shard
    assert sum(per_shard) == len(jobs)


async def test_unstamped_submits_are_forwarded_to_owner():
    jobs = [f"fw-{i}" for i in range(16)]
    results, engines = await _run_cluster(2, jobs, stamped=False)
    assert all(s == "SUCCEEDED" for s, _ in results.values())
    forwarded = sum(
        e.metrics.shard_forwarded.value(kind="submit", shard=str(e.shard_index))
        for e in engines
    )
    assert forwarded > 0  # round-robin guarantees some landed on non-owners


async def test_dead_shard_jobs_stay_pending_and_recover_on_restart():
    """Degraded mode: with shard 1 stopped, shard-0 jobs still complete and
    shard-1 jobs park in PENDING (no silent loss, no bogus terminal state);
    a restarted shard 1 picks them up on replay."""
    kv = MemoryKV()
    bus = LoopbackBus()
    js = JobStore(kv)
    eng0 = _mk_engine(bus, kv, index=0, count=2)
    await eng0.start()  # shard 1 is down
    await _attach_worker(bus)

    jobs = [f"dg-{i}" for i in range(24)]
    live = [j for j in jobs if partition_of(j, 2) == 0]
    dead = [j for j in jobs if partition_of(j, 2) == 1]
    assert live and dead  # both partitions represented
    for jid in jobs:
        # gateway-style submit: PENDING meta + request blob precede the bus
        # publish, so an unowned job is durably visible, not lost
        await js.set_state(jid, JobState.PENDING,
                           fields={"topic": "job.bench"}, event="submit")
        await js.put_request(JobRequest(job_id=jid, topic="job.bench",
                                        tenant_id="default"))
        await bus.publish(
            subj.submit_subject_for(jid, 2),
            BusPacket.wrap(JobRequest(job_id=jid, topic="job.bench",
                                      tenant_id="default"), sender_id="t"),
        )
    for _ in range(2000):
        await bus.drain()
        if await _all_succeeded(js, live):
            break
        await asyncio.sleep(0.005)
    for jid in live:
        assert await js.get_state(jid) == "SUCCEEDED"
    for jid in dead:
        # schedulable-after-restart: still PENDING, request blob intact
        assert await js.get_state(jid) == "PENDING"
        assert await js.get_request(jid) is not None

    # the LIVE shard's replayer must not steal the dead shard's jobs …
    from cordum_tpu.controlplane.scheduler.reconciler import PendingReplayer
    from cordum_tpu.infra.config import Timeouts

    assert await PendingReplayer(eng0, js, Timeouts(pending_replay_s=0.0)).run_once() == 0
    for jid in dead:
        assert await js.get_state(jid) == "PENDING"

    # … while a RESTARTED owner shard replays them to completion
    eng1 = _mk_engine(bus, kv, index=1, count=2)
    await eng1.start()
    await PendingReplayer(eng1, js, Timeouts(pending_replay_s=0.0)).run_once()
    for _ in range(2000):
        await bus.drain()
        if await _all_succeeded(js, dead):
            break
        await asyncio.sleep(0.005)
    for jid in dead:
        assert await js.get_state(jid) == "SUCCEEDED"
    await eng0.stop()
    await eng1.stop()
    await bus.close()


async def test_progress_recorded_once_across_shards():
    """Progress fans out to every shard; only the owner appends the event."""
    from cordum_tpu.protocol.types import JobProgress

    kv = MemoryKV()
    bus = LoopbackBus()
    engines = [_mk_engine(bus, kv, index=i, count=2) for i in range(2)]
    for e in engines:
        await e.start()
    jid = "prog-1"
    await bus.publish(
        subj.PROGRESS,
        BusPacket.wrap(JobProgress(job_id=jid, percent=50.0, message="half"),
                       sender_id="w1"),
    )
    await bus.drain()
    events = await JobStore(kv).events(jid)
    assert len([e for e in events if e["event"] == "progress"]) == 1
    for e in engines:
        await e.stop()
    await bus.close()


# ---------------------------------------------------------------------------
# partitioned KV
# ---------------------------------------------------------------------------


async def test_partitioned_kv_job_keys_colocate():
    parts = [MemoryKV(), MemoryKV()]
    kv = PartitionedKV(parts)
    js = JobStore(kv)
    jid = "colo-1"
    await js.set_state(jid, JobState.PENDING, fields={"topic": "t"}, event="submit")
    await js.put_request(JobRequest(job_id=jid, topic="t"))
    home = partition_of(jid, 2)
    # meta, request, events all live on the job's home partition only
    for key in (f"job:meta:{jid}", f"job:request:{jid}", f"job:events:{jid}"):
        assert await parts[home].version(key) > 0, key
        assert await parts[1 - home].version(key) == 0, key
    # reads through the facade see them
    assert (await js.get_meta(jid)).get("topic") == "t"
    assert await js.get_request(jid) is not None


async def test_partitioned_kv_merged_indexes():
    kv = PartitionedKV([MemoryKV(), MemoryKV()])
    js = JobStore(kv)
    jobs = [f"idx-{i}" for i in range(12)]
    for jid in jobs:
        await js.set_state(jid, JobState.PENDING, fields={"topic": "t"}, event="s")
    # state index + recent merge across partitions
    assert sorted(await js.list_by_state("PENDING", 100)) == sorted(jobs)
    assert set(await js.list_recent(100)) == set(jobs)
    assert await kv.zcard("job:index:PENDING") == len(jobs)
    # transitions move ids between the merged indexes
    for jid in jobs[:5]:
        await js.set_state(jid, JobState.CANCELLED, event="cancel")
    assert sorted(await js.list_by_state("CANCELLED", 100)) == sorted(jobs[:5])
    assert len(await js.list_by_state("PENDING", 100)) == len(jobs) - 5


async def test_partitioned_kv_trace_and_tenant_sets():
    kv = PartitionedKV([MemoryKV(), MemoryKV(), MemoryKV()])
    js = JobStore(kv)
    jobs = [f"tr-{i}" for i in range(9)]
    for jid in jobs:
        await js.add_to_trace("trace-A", jid)
        await js.tenant_active_add("acme", jid)
    assert await js.trace("trace-A") == set(jobs)
    assert await js.tenant_active_count("acme") == len(jobs)
    for jid in jobs:
        await js.tenant_active_remove("acme", jid)
    assert await js.tenant_active_count("acme") == 0


async def test_partitioned_kv_global_delete_broadcasts():
    kv = PartitionedKV([MemoryKV(), MemoryKV()])
    for i in range(8):
        await kv.zadd("job:recent", f"jr-{i}", float(i))
    assert await kv.zcard("job:recent") == 8
    await kv.delete("job:recent")
    assert await kv.zcard("job:recent") == 0


async def test_partitioned_kv_pipe_is_atomic_on_home_partition():
    parts = [MemoryKV(), MemoryKV()]
    kv = PartitionedKV(parts)
    jid = "pipe-1"
    key = f"job:meta:{jid}"
    ok, versions = await kv.pipe_execute(
        {key: 0},
        [("hset", key, {"state": b"PENDING"}),
         ("zadd", "job:index:PENDING", jid, 1.0)],
    )
    assert ok and versions[key] > 0
    home = partition_of(jid, 2)
    assert await parts[home].zcard("job:index:PENDING") == 1
    assert await parts[1 - home].zcard("job:index:PENDING") == 0
    # conflicting watch rejects without touching state
    ok2, _ = await kv.pipe_execute({key: 0}, [("hset", key, {"state": b"X"})])
    assert not ok2
    assert (await kv.hgetall(key))["state"] == b"PENDING"


# ---------------------------------------------------------------------------
# partitioned statebus over live TCP (+ coalesced wire path)
# ---------------------------------------------------------------------------


@pytest.mark.statebus
async def test_partitioned_statebus_end_to_end():
    srvs = [StateBusServer(port=0), StateBusServer(port=0)]
    for s in srvs:
        await s.start()
    urls = ",".join(f"statebus://127.0.0.1:{s.port}" for s in srvs)
    kv, bus, grp = await connect_partitioned(urls)
    try:
        assert isinstance(kv, PartitionedKV) and isinstance(bus, PartitionedBus)
        assert await kv.ping() and await bus.ping()
        # keyspace routing round-trips through real wire partitions
        for i in range(10):
            await kv.set(f"wire-{i}", str(i).encode())
        for i in range(10):
            assert await kv.get(f"wire-{i}") == str(i).encode()
        assert sorted(await kv.keys("wire-")) == sorted(f"wire-{i}" for i in range(10))
        # concrete-subject pub/sub with a queue group + wildcard fanout
        got: list[tuple[str, str]] = []
        done = asyncio.Event()

        async def on_concrete(subject, pkt):
            got.append(("q", subject))
            if len(got) >= 4:
                done.set()

        async def on_wild(subject, pkt):
            got.append(("w", subject))
            if len(got) >= 4:
                done.set()

        await bus.subscribe("sys.job.submit.0", on_concrete, queue="g")
        await bus.subscribe("sys.job.submit.>", on_wild)
        for jid in ("a", "b"):
            await bus.publish(
                "sys.job.submit.0",
                BusPacket.wrap(JobRequest(job_id=jid, topic="t"), sender_id="t"),
            )
        await asyncio.wait_for(done.wait(), 10)
        assert len([g for g in got if g[0] == "q"]) == 2
        assert len([g for g in got if g[0] == "w"]) == 2
        # the coalescing writer actually batched frames server-side
        coalesced = 0
        for s in srvs:
            text = s.metrics.render()
            for line in text.splitlines():
                if line.startswith("cordum_statebus_coalesced_batch_count"):
                    coalesced += float(line.rsplit(" ", 1)[1])
        assert coalesced > 0
    finally:
        await grp.close()
        for s in srvs:
            await s.stop()


@pytest.mark.statebus
async def test_sharded_engines_over_partitioned_statebus():
    """Two engine shards + a worker over two real statebus partitions: the
    full wire topology of the sharded bench, in miniature."""
    srvs = [StateBusServer(port=0), StateBusServer(port=0)]
    for s in srvs:
        await s.start()
    urls = ",".join(f"statebus://127.0.0.1:{s.port}" for s in srvs)
    conns = []
    engines = []
    try:
        for i in range(2):
            kv, bus, grp = await connect_partitioned(urls)
            conns.append(grp)
            eng = _mk_engine(bus, kv, index=i, count=2)
            engines.append(eng)
            await eng.start()
        wkv, wbus, wgrp = await connect_partitioned(urls)
        conns.append(wgrp)
        await _attach_worker(wbus)
        jobs = [f"sb-{i}" for i in range(16)]
        for jid in jobs:
            await wbus.publish(
                subj.submit_subject_for(jid, 2),
                BusPacket.wrap(JobRequest(job_id=jid, topic="job.bench",
                                          tenant_id="default"), sender_id="t"),
            )
        js = JobStore(wkv)
        for _ in range(400):
            if await _all_succeeded(js, jobs):
                break
            await asyncio.sleep(0.025)
        assert await _all_succeeded(js, jobs)
        split = [e.metrics.shard_scheduled.value(shard=str(e.shard_index)) for e in engines]
        assert sum(split) == len(jobs) and all(v > 0 for v in split), split
    finally:
        for eng in engines:
            await eng.stop()
        for grp in conns:
            await grp.close()
        for s in srvs:
            await s.stop()
