"""Signed policy bundles: ed25519 verification, fail-closed on bad/missing
signatures; CLI arg-parsing smoke."""
import pytest

from cordum_tpu.controlplane.safetykernel.kernel import SafetyKernel, verify_signature
from cordum_tpu.protocol.types import PolicyCheckRequest

POLICY = b"default_tenant: default\ntenants:\n  default:\n    allow_topics: ['job.*']\n"


def make_keys():
    """(signer, raw-32-byte pubkey) via the cryptography backend when
    installed, else the pure-Python fallback the kernel also verifies with."""
    try:
        from cryptography.hazmat.primitives.asymmetric.ed25519 import Ed25519PrivateKey
        from cryptography.hazmat.primitives.serialization import (
            Encoding, PublicFormat,
        )
    except ImportError:
        from cordum_tpu.utils.ed25519 import SigningKey

        priv = SigningKey()
        return priv, priv.public_key_bytes()

    priv = Ed25519PrivateKey.generate()
    pub = priv.public_key().public_bytes(Encoding.Raw, PublicFormat.Raw)
    return priv, pub


def test_verify_signature_roundtrip():
    priv, pub = make_keys()
    sig = priv.sign(POLICY)
    assert verify_signature(POLICY, sig, pub)
    assert not verify_signature(POLICY + b"tampered", sig, pub)
    assert not verify_signature(POLICY, b"junk", pub)


async def test_kernel_accepts_valid_signature(tmp_path):
    priv, pub = make_keys()
    ppath = tmp_path / "safety.yaml"
    ppath.write_bytes(POLICY)
    (tmp_path / "safety.yaml.sig").write_bytes(priv.sign(POLICY))
    kpath = tmp_path / "policy.pub"
    kpath.write_bytes(pub)
    kernel = SafetyKernel(policy_path=str(ppath), public_key_path=str(kpath))
    await kernel.reload()
    resp = await kernel.check(PolicyCheckRequest(topic="job.ok"))
    assert resp.decision == "ALLOW"
    resp = await kernel.check(PolicyCheckRequest(topic="other.x"))
    assert resp.decision == "DENY"  # tenant allowlist from the signed file


async def test_kernel_rejects_tampered_policy(tmp_path):
    priv, pub = make_keys()
    ppath = tmp_path / "safety.yaml"
    ppath.write_bytes(POLICY)
    (tmp_path / "safety.yaml.sig").write_bytes(priv.sign(POLICY))
    kpath = tmp_path / "policy.pub"
    kpath.write_bytes(pub)
    kernel = SafetyKernel(policy_path=str(ppath), public_key_path=str(kpath))
    await kernel.reload()
    # attacker rewrites the policy file to allow everything, without the key
    ppath.write_bytes(b"tenants: {}\nrules: []\n")
    snap_before = kernel.snapshot_id
    await kernel.reload()
    assert kernel.snapshot_id == snap_before  # fail-closed: old policy kept
    resp = await kernel.evaluate_raw(PolicyCheckRequest(topic="other.x"))
    assert resp.decision == "DENY"


async def test_kernel_missing_sig_rejected(tmp_path):
    _, pub = make_keys()
    ppath = tmp_path / "safety.yaml"
    ppath.write_bytes(POLICY)
    kpath = tmp_path / "policy.pub"
    kpath.write_bytes(pub)
    kernel = SafetyKernel(policy_path=str(ppath), public_key_path=str(kpath))
    await kernel.reload()
    # no .sig and nothing verified ever installed → deny-all sentinel
    resp = await kernel.evaluate_raw(PolicyCheckRequest(topic="job.x"))
    assert resp.decision == "DENY"
    assert "unverified" in resp.reason
    # once a valid signature lands, the real policy takes over
    priv, pub2 = make_keys()
    kpath.write_bytes(pub2)
    (tmp_path / "safety.yaml.sig").write_bytes(priv.sign(POLICY))
    await kernel.reload()
    resp = await kernel.evaluate_raw(PolicyCheckRequest(topic="job.x"))
    assert resp.decision == "ALLOW"


async def test_kernel_missing_policy_file_fails_closed(tmp_path):
    """Deleting/mis-pathing a signed policy file must not disable enforcement
    (advisor finding: the FileNotFoundError fallback previously reverted to
    the unsigned in-memory doc → default allow)."""
    priv, pub = make_keys()
    ppath = tmp_path / "safety.yaml"
    ppath.write_bytes(POLICY)
    (tmp_path / "safety.yaml.sig").write_bytes(priv.sign(POLICY))
    kpath = tmp_path / "policy.pub"
    kpath.write_bytes(pub)
    kernel = SafetyKernel(policy_path=str(ppath), public_key_path=str(kpath))
    await kernel.reload()
    snap = kernel.snapshot_id
    # attacker deletes the policy file → previous verified policy is kept
    ppath.unlink()
    await kernel.reload()
    assert kernel.snapshot_id == snap
    resp = await kernel.evaluate_raw(PolicyCheckRequest(topic="other.x"))
    assert resp.decision == "DENY"  # still the signed tenant allowlist

    # pubkey configured but the policy file NEVER existed → deny-all sentinel
    kernel2 = SafetyKernel(
        policy_path=str(tmp_path / "nope.yaml"), public_key_path=str(kpath)
    )
    await kernel2.reload()
    resp = await kernel2.evaluate_raw(PolicyCheckRequest(topic="job.x"))
    assert resp.decision == "DENY"
    assert "fail-closed" in resp.reason or "unverified" in resp.reason


async def test_kernel_fragments_still_merge_while_file_missing(tmp_path, kv):
    """Fail-closed on a missing signed file must NOT freeze the policy:
    configsvc fragments pushed while the file is absent still apply."""
    from cordum_tpu.infra.configsvc import ConfigService

    priv, pub = make_keys()
    ppath = tmp_path / "safety.yaml"
    ppath.write_bytes(POLICY)
    (tmp_path / "safety.yaml.sig").write_bytes(priv.sign(POLICY))
    kpath = tmp_path / "policy.pub"
    kpath.write_bytes(pub)
    cs = ConfigService(kv)
    kernel = SafetyKernel(
        policy_path=str(ppath), public_key_path=str(kpath), configsvc=cs
    )
    await kernel.reload()
    assert (await kernel.evaluate_raw(PolicyCheckRequest(topic="job.x"))).decision == "ALLOW"
    ppath.unlink()
    # admin pushes a deny fragment while the file is missing
    await cs.set("system", "policy/deny-x", {
        "enabled": True,
        "rules": [{"id": "block-x", "match": {"topics": ["job.x"]}, "decision": "deny"}],
    })
    await kernel.reload()
    resp = await kernel.evaluate_raw(PolicyCheckRequest(topic="job.x"))
    assert resp.decision == "DENY"  # fragment merged despite missing file
    # and the verified file policy is still enforced underneath
    assert (await kernel.evaluate_raw(PolicyCheckRequest(topic="job.other"))).decision == "ALLOW"
    assert (await kernel.evaluate_raw(PolicyCheckRequest(topic="nope.x"))).decision == "DENY"


# ---------------------------------------------------------------- CLI

def test_cli_parser_covers_commands():
    from cordum_tpu.cli import build_parser

    p = build_parser()
    args = p.parse_args(["job", "submit", "--topic", "job.x", "--payload", "{}", "--wait"])
    assert args.command == "job" and args.topic == "job.x" and args.wait
    args = p.parse_args(["run", "start", "wf1", "--input", "{\"a\":1}"])
    assert args.action == "start"
    args = p.parse_args(["approval", "approve", "j123"])
    assert args.job_id == "j123"
    args = p.parse_args(["pack", "install", "examples/hello-pack"])
    assert args.target == "examples/hello-pack"
    args = p.parse_args(["up", "--logdir", "/tmp/x", "statebus", "gateway"])
    assert args.services == ["statebus", "gateway"]


def test_cli_init_scaffolds(tmp_path, monkeypatch):
    from cordum_tpu.cli import cmd_init

    monkeypatch.chdir(tmp_path)

    class A:
        force = False

    cmd_init(A())
    assert (tmp_path / "config" / "pools.yaml").exists()
    assert (tmp_path / "config" / "safety.yaml").exists()
    # idempotent without --force
    cmd_init(A())
