"""Speculative decoding inside the ragged step (ISSUE 19, docs/SERVING.md
§Speculative decoding): the n-gram/prompt-lookup drafter, accept-longest-
prefix verification semantics (token-exact vs the sequential oracle on
both the fake and the real fp32 paged backend, drafts crossing page
boundaries and CoW prefix pages), write-position rollback arena
bit-identity, the spec-disabled legacy-path identity guard, adaptive-k
throttling, burst stream-offset exactly-once regressions (worker sink,
scheduler fold, SDK dedupe, failover resume replay), and the capacity
surface (occupancy beacon key, `cordumctl capacity` accept column, the
ServingPlacer's speculable preference)."""
import asyncio
import random

from cordum_tpu.controlplane.scheduler.placer import ServingPlacer
from cordum_tpu.infra.metrics import Metrics
from cordum_tpu.serving.backend import StepEntry
from cordum_tpu.serving.engine import (
    DEFAULT_DRAFT_K,
    GenRequest,
    ServingEngine,
)
from cordum_tpu.serving.pager import PageAllocator
from cordum_tpu.sdk.client import merge_stream_packet

from .test_serving import FakeBackend, fake_ref, run_blocking

MOD = 251  # the FakeBackend recurrence modulus


# ---------------------------------------------------------------------------
# a draft-capable FakeBackend + scripted drafters
# ---------------------------------------------------------------------------


class SpecFakeBackend(FakeBackend):
    """FakeBackend extended with the draft-row contract: a ``draft > 0``
    entry returns one next-token prediction per fed position — the same
    position-local recurrence ``(token * 3 + position) % 251`` the decode
    rows use, so the engine's accept-longest-prefix logic is exercised
    against an exact oracle."""

    supports_draft = True

    def step(self, entries):
        base = super().step(entries)
        out = []
        for e, tok in zip(entries, base):
            if getattr(e, "draft", 0) > 0:
                out.append([(e.tokens[i] * 3 + (e.start + i)) % MOD
                            for i in range(len(e.tokens))])
            else:
                out.append(tok)
        return out


class RecordingBackend(FakeBackend):
    """Plain (non-draft-capable) backend that records every StepEntry —
    the spec-disabled identity guard reads the metadata off it."""

    def __init__(self, **kw):
        super().__init__(**kw)
        self.seen: list[list[tuple]] = []

    def step(self, entries):
        self.seen.append([
            (list(e.tokens), e.start, e.phase, getattr(e, "draft", 0))
            for e in entries
        ])
        return super().step(entries)


def perfect_drafter(history, k):
    """The fake recurrence's exact continuation: token at sequence index
    j is ``(token[j-1] * 3 + (j - 1)) % 251``, so every draft verifies."""
    h = list(history)
    out = []
    for _ in range(k):
        nxt = (h[-1] * 3 + len(h) - 1) % MOD
        out.append(nxt)
        h.append(nxt)
    return out


def garbage_drafter(history, k):
    """Never-correct drafts: every proposal is the true continuation
    plus one, so every draft is rejected and each step degrades to a
    single verified token (the worst-case rollback path)."""
    return [(t + 1) % MOD for t in perfect_drafter(history, k)]


def cut2_drafter(history, k):
    """Correct for the first two positions, garbage after — exercises
    partial accept + rollback in the same row."""
    plan = perfect_drafter(history, k)
    return [t if i < 2 else (t + 1) % MOD for i, t in enumerate(plan)]


# ---------------------------------------------------------------------------
# n-gram drafter units
# ---------------------------------------------------------------------------


def test_ngram_draft_proposes_template_continuation():
    motif = [5, 9, 14, 23]
    history = motif * 3 + motif[:2]  # mid-motif: the tail bigram repeats
    draft = ServingEngine._ngram_draft(history, 4)
    # the continuation after the most recent earlier [14, 23, 5]... match
    # is the motif's next tokens
    assert draft == [14, 23, 5, 9]


def test_ngram_draft_most_recent_occurrence_wins():
    # the trigram [1, 2, 3] occurs twice with different continuations;
    # the LATER one (-> 9) must win over the earlier (-> 7)
    history = [1, 2, 3, 7, 0, 1, 2, 3, 9, 4, 1, 2, 3]
    assert ServingEngine._ngram_draft(history, 1) == [9]


def test_ngram_draft_no_repetition_returns_empty():
    assert ServingEngine._ngram_draft(list(range(40)), 4) == []
    assert ServingEngine._ngram_draft([7], 4) == []


def test_ngram_draft_respects_k():
    history = [1, 2, 3, 4, 5, 6, 1, 2, 3]
    assert len(ServingEngine._ngram_draft(history, 2)) <= 2


# ---------------------------------------------------------------------------
# engine semantics on the fake backend
# ---------------------------------------------------------------------------


async def _run_engine(backend, prompts, max_new, **eng_kw):
    eng = ServingEngine(backend, run_blocking=run_blocking,
                        max_new_tokens_cap=max_new, **eng_kw)
    results = await asyncio.gather(*[
        eng.submit(GenRequest(prompt=p, max_new_tokens=max_new, stream=False),
                   job_id=f"j{i}")
        for i, p in enumerate(prompts)
    ])
    outs = [r["tokens"] for r in results]
    await eng.stop()
    return outs, eng


async def test_spec_engine_token_identical_and_fewer_steps():
    """Perfectly drafted sessions produce EXACTLY the sequential tokens in
    far fewer backend steps — speculation is a schedule change, not a math
    change."""
    prompts = [[5, 9, 17, 3], [100, 42], [7, 3, 11]]
    base_be = SpecFakeBackend()
    base_outs, base_eng = await _run_engine(base_be, prompts, 12,
                                            speculative=False)
    spec_be = SpecFakeBackend()
    spec_outs, spec_eng = await _run_engine(spec_be, prompts, 12,
                                            speculative=True, draft_k=4,
                                            drafter=perfect_drafter)
    for p, out in zip(prompts, spec_outs):
        assert out == fake_ref(p, 12)
    assert spec_outs == base_outs
    assert spec_be.steps < base_be.steps
    assert spec_eng.stats.spec_steps > 0
    assert spec_eng.stats.accepted_tokens == spec_eng.stats.drafted_tokens > 0
    assert spec_eng.stats.rolled_back_tokens == 0
    assert spec_eng.spec_accept_ewma > 0.5
    # both engines count the same generated tokens
    assert spec_eng.stats.decoded_tokens == base_eng.stats.decoded_tokens


async def test_spec_engine_garbage_drafts_roll_back_token_identical():
    """Every draft rejected: output still exactly sequential (the bonus
    token carries each step), every proposal counted as rolled back."""
    prompts = [[5, 9, 17, 3], [8, 1]]
    outs, eng = await _run_engine(SpecFakeBackend(), prompts, 10,
                                  speculative=True, draft_k=4,
                                  drafter=garbage_drafter)
    for p, out in zip(prompts, outs):
        assert out == fake_ref(p, 10)
    assert eng.stats.rolled_back_tokens > 0
    assert eng.stats.accepted_tokens == 0
    # per-session EWMAs decayed: the engine stopped proposing long drafts
    assert eng.spec_accept_ewma < 0.5


async def test_spec_engine_partial_accept_rolls_back_tail():
    """A row that verifies 2 of k drafts advances exactly 3 tokens (2
    accepted + the bonus) and rolls back the rest — still token-exact."""
    prompt = [5, 9, 17, 3]
    outs, eng = await _run_engine(SpecFakeBackend(), [prompt], 12,
                                  speculative=True, draft_k=4,
                                  drafter=cut2_drafter)
    assert outs[0] == fake_ref(prompt, 12)
    assert eng.stats.accepted_tokens > 0
    assert eng.stats.rolled_back_tokens > 0


async def test_spec_gated_off_without_backend_support():
    """A backend without ``supports_draft`` keeps the legacy path
    byte-identical: no draft metadata, single-token decode rows, same
    outputs — even with ``speculative=True`` requested."""
    prompts = [[5, 9, 17, 3], [100, 42]]
    be = RecordingBackend()
    outs, eng = await _run_engine(be, prompts, 8,
                                  speculative=True, draft_k=4,
                                  drafter=perfect_drafter)
    assert eng.speculative is False
    for p, out in zip(prompts, outs):
        assert out == fake_ref(p, 8)
    for step in be.seen:
        for tokens, _start, phase, draft in step:
            assert draft == 0
            if phase == "decode":
                assert len(tokens) == 1
    assert eng.stats.drafted_tokens == 0 and eng.stats.spec_steps == 0


async def test_spec_flag_off_never_drafts_on_capable_backend():
    class RecordingSpecBackend(SpecFakeBackend, RecordingBackend):
        pass

    be = RecordingSpecBackend()
    outs, eng = await _run_engine(be, [[5, 9, 17, 3]], 8, speculative=False)
    assert eng.speculative is False
    assert outs[0] == fake_ref([5, 9, 17, 3], 8)
    assert all(draft == 0 for step in be.seen for *_, draft in step)


async def test_adaptive_k_ramps_down_on_rejection():
    """The per-session acceptance EWMA throttles proposal length: a
    session starts at full draft_k and decays toward single-token probes
    while its drafts keep rejecting; k never exceeds remaining - 1."""
    seen: list[tuple[int, int]] = []  # (k asked of the drafter, room left)
    prompt, max_new = [5, 9, 17, 3], 16

    def capture(history, k):
        seen.append((k, max_new - (len(history) - len(prompt))))
        return garbage_drafter(history, k)

    outs, _ = await _run_engine(SpecFakeBackend(), [prompt], max_new,
                                speculative=True, draft_k=4, drafter=capture)
    assert outs[0] == fake_ref(prompt, max_new)
    assert seen[0][0] == 4  # optimistic start: EWMA seeds at 1.0
    assert seen[-1][0] == 1  # decayed to probes after steady rejection
    assert all(k <= room - 1 for k, room in seen)  # the overshoot clamp


async def test_spec_burst_never_overshoots_max_new():
    """Fully accepted bursts land EXACTLY max_new tokens — the k <=
    remaining - 1 clamp means a burst can never write past the admitted
    page footprint."""
    for max_new in (3, 7, 12):
        outs, _ = await _run_engine(SpecFakeBackend(), [[5, 9, 17, 3]],
                                    max_new, speculative=True, draft_k=4,
                                    drafter=perfect_drafter)
        assert outs[0] == fake_ref([5, 9, 17, 3], max_new)
        assert len(outs[0]) == max_new


async def test_eos_inside_burst_truncates_exactly():
    prompt = [5, 9]
    seq = fake_ref(prompt, 12)
    eos = seq[5]
    expected = seq[:seq.index(eos) + 1]
    eng = ServingEngine(SpecFakeBackend(), run_blocking=run_blocking,
                        max_new_tokens_cap=12, speculative=True, draft_k=4,
                        drafter=perfect_drafter)
    r = await eng.submit(GenRequest(prompt=prompt, max_new_tokens=12,
                                    stream=False, eos_token=eos),
                         job_id="e1")
    await eng.stop()
    assert r["tokens"] == expected


async def test_spec_metrics_counters():
    metrics = Metrics()
    await _run_engine(SpecFakeBackend(), [[5, 9, 17, 3]], 10,
                      speculative=True, draft_k=4, drafter=cut2_drafter,
                      metrics=metrics)
    drafted = metrics.serving_spec_drafted.value()
    accepted = metrics.serving_spec_accepted.value()
    rolled = metrics.serving_spec_rolled_back.value()
    assert drafted > 0 and accepted > 0 and rolled > 0
    assert drafted == accepted + rolled


# ---------------------------------------------------------------------------
# real fp32 paged backend: oracle exactness + arena bit-identity
# ---------------------------------------------------------------------------


def _llama_env():
    import jax
    import jax.numpy as jnp

    from cordum_tpu.models import llama
    from cordum_tpu.serving.backend import LlamaServingBackend

    cfg = llama.LlamaConfig(vocab_size=256, d_model=64, n_layers=2, n_heads=4,
                            n_kv_heads=2, d_ff=128, max_seq_len=128,
                            dtype=jnp.float32)
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    backend = LlamaServingBackend(
        cfg, num_pages=64, page_size=8, params_provider=lambda: params
    )
    return cfg, params, backend


def _oracle_cut_drafter(refs, rng):
    """Drafter scripted from precomputed oracle sequences: the true
    continuation up to a random cut, garbage after — controlled accept
    lengths against the real model."""

    def drafter(history, k):
        for seq in refs:
            if len(seq) > len(history) and seq[:len(history)] == history:
                cont = seq[len(history):len(history) + k]
                cut = rng.randint(0, len(cont))
                return cont[:cut] + [(t + 1) % 256 for t in cont[cut:]]
        return []

    return drafter


async def test_spec_real_backend_property_matches_oracle():
    """Property: speculative decode on the real fp32 paged backend is
    token-exact vs the sequential full-forward oracle across sessions
    whose drafts cross page boundaries (page_size=8, bursts up to 5
    tokens) with randomized accept cut points."""
    from .test_serving import ref_greedy

    cfg, params, be = _llama_env()
    rng = random.Random(7)
    prompts = [[5, 9, 17, 3], [7, 3, 11, 19, 2, 5, 23, 1, 13], [100, 42]]
    n_new = 14
    refs = [p + ref_greedy(cfg, params, p, n_new) for p in prompts]
    eng = ServingEngine(be, run_blocking=run_blocking,
                        max_new_tokens_cap=n_new, speculative=True,
                        draft_k=4, drafter=_oracle_cut_drafter(refs, rng))
    assert eng.speculative is True
    results = await asyncio.gather(*[
        eng.submit(GenRequest(prompt=p, max_new_tokens=n_new, stream=False),
                   job_id=f"real{i}")
        for i, p in enumerate(prompts)
    ])
    stats = eng.stats
    await eng.stop()
    for p, seq, r in zip(prompts, refs, results):
        assert r["tokens"] == seq[len(p):], p
    assert stats.accepted_tokens > 0  # speculation actually engaged
    assert stats.rolled_back_tokens > 0  # ... and rollback was exercised


async def test_spec_with_cow_prefix_pages_matches_oracle():
    """Speculative bursts over copy-on-write shared-prefix pages: a
    second session reusing a cached full-page prefix must still be
    token-exact — the draft write span triggers the CoW guard before any
    shared page is written."""
    from .test_serving import ref_greedy

    cfg, params, be = _llama_env()
    rng = random.Random(11)
    system = [7, 3, 11, 19, 2, 5, 23, 1]  # exactly one 8-slot page
    p1, p2 = system + [13, 4], system + [9, 2]
    n_new = 8
    refs = [p + ref_greedy(cfg, params, p, n_new) for p in (p1, p2)]
    eng = ServingEngine(be, run_blocking=run_blocking,
                        max_new_tokens_cap=n_new, speculative=True,
                        draft_k=4, drafter=_oracle_cut_drafter(refs, rng))
    assert eng.prefix is not None  # the real backend carries copy_page
    out1 = await eng.submit(
        GenRequest(prompt=p1, max_new_tokens=n_new, stream=False),
        job_id="cow1")
    out2 = await eng.submit(
        GenRequest(prompt=p2, max_new_tokens=n_new, stream=False),
        job_id="cow2")
    stats = eng.stats
    await eng.stop()
    assert out1["tokens"] == refs[0][len(p1):]
    assert out2["tokens"] == refs[1][len(p2):]
    assert stats.prefix_hits >= 1  # the second session mapped shared pages
    assert stats.accepted_tokens > 0


async def test_rollback_arena_bit_identical_to_sequential():
    """The write-position rollback invariant, measured at the arena: a
    speculative session's K/V over [0, pos) is byte-identical to a
    sequential session's — rejected-draft garbage beyond pos never
    reaches exported (= reachable) state."""
    from .test_serving import ref_greedy

    cfg, params, be = _llama_env()
    alloc = PageAllocator(be.num_pages, be.page_size)
    prompt = [7, 3, 11, 19, 2, 5, 23, 1, 13]  # crosses a page boundary
    n_new = 10
    ref = ref_greedy(cfg, params, prompt, n_new)
    seq = prompt + ref
    total = len(prompt) + n_new

    # sequential leg
    pages_a = alloc.alloc("seq", alloc.pages_for(total))
    first = be.prefill(prompt, pages_a)
    out_a, pos_a, last = [first], len(prompt), first
    while len(out_a) < n_new:
        (nxt,) = be.decode([(last, pos_a, pages_a)])
        pos_a, last = pos_a + 1, int(nxt)
        out_a.append(last)

    # speculative leg: manual draft rows with random cut points, engine
    # accept semantics, write-position rollback
    rng = random.Random(3)
    pages_b = alloc.alloc("spec", alloc.pages_for(total))
    first = be.prefill(prompt, pages_b)
    out_b, pos_b, last = [first], len(prompt), first
    while len(out_b) < n_new:
        room = n_new - len(out_b)
        k = min(4, room - 1)
        if k < 1:
            (nxt,) = be.decode([(last, pos_b, pages_b)])
            pos_b, last = pos_b + 1, int(nxt)
            out_b.append(last)
            continue
        idx = len(prompt) + len(out_b)
        cont = seq[idx:idx + k]
        cut = rng.randint(0, len(cont))
        draft = cont[:cut] + [(t + 1) % 256 for t in cont[cut:]]
        (preds,) = be.step([StepEntry(
            tokens=[last, *draft], start=pos_b, pages=pages_b, sample=True,
            phase="decode", key="spec", draft=len(draft))])
        preds = [int(t) for t in preds]
        a = 0
        while a < len(draft) and draft[a] == preds[a]:
            a += 1
        burst = draft[:a] + [preds[a]]
        out_b.extend(burst)
        pos_b += len(burst)  # rollback: rejected drafts sit at >= pos_b
        last = burst[-1]

    assert out_a == out_b == ref
    # both legs wrote identical tokens at positions [0, total - 1); the
    # final sampled token is never fed on the sequential leg, so compare
    # up to there — export trims to live positions host-side
    written = total - 1
    rec_a = be.export_kv(pages_a, 0, written)
    rec_b = be.export_kv(pages_b, 0, written)
    assert len(rec_a) == len(rec_b) > 1
    for ra, rb in zip(rec_a, rec_b):
        assert ra["i"] == rb["i"] and ra["used"] == rb["used"]
        assert ra["k"] == rb["k"], f"K pages differ at ordinal {ra['i']}"
        assert ra["v"] == rb["v"], f"V pages differ at ordinal {ra['i']}"


# ---------------------------------------------------------------------------
# burst stream offsets: exactly-once across multi-token packets
# ---------------------------------------------------------------------------


def test_scheduler_record_stream_merges_burst_packets():
    """The scheduler's per-job stream fold (failover resume_tokens source)
    merges multi-token packets by offset: bursts append, replays
    overwrite idempotently, out-of-order duplicates never corrupt."""
    from cordum_tpu.controlplane.scheduler.engine import Engine

    class Stub:
        _stream_tokens: dict = {}

    stub = Stub()
    rec = Engine._record_stream
    rec(stub, "j", 0, [10, 11, 12])  # a 3-token burst
    rec(stub, "j", 3, [13])
    rec(stub, "j", 4, [14, 15])
    assert stub._stream_tokens["j"] == [10, 11, 12, 13, 14, 15]
    # failover replay at offset 0 (the whole prefix re-streams) is a no-op
    rec(stub, "j", 0, [10, 11, 12, 13])
    assert stub._stream_tokens["j"] == [10, 11, 12, 13, 14, 15]
    # an overlapping burst (re-sent tail + fresh tokens) extends exactly
    rec(stub, "j", 5, [15, 16, 17])
    assert stub._stream_tokens["j"] == [10, 11, 12, 13, 14, 15, 16, 17]
    # a gapped packet is dropped (backfilled by the next offset-0 replay)
    rec(stub, "j", 12, [99])
    assert stub._stream_tokens["j"] == [10, 11, 12, 13, 14, 15, 16, 17]


def test_sdk_merge_stream_packet_burst_dedupe():
    """The SDK's offset dedupe assembles an exactly-once sequence from
    multi-token burst packets, including a failed-over worker's replay of
    the streamed prefix at offset 0."""
    n_seen, got = 0, []
    for off, toks in [(0, [1, 2, 3]), (3, [4]), (4, [5, 6, 7])]:
        fresh, n_seen = merge_stream_packet(n_seen, off, toks)
        got.extend(fresh)
    assert got == [1, 2, 3, 4, 5, 6, 7]
    # failover: the new worker replays everything at offset 0 as one
    # burst, then continues — duplicates skipped, the tail lands once
    fresh, n_seen = merge_stream_packet(n_seen, 0, [1, 2, 3, 4, 5, 6, 7, 8])
    got.extend(fresh)
    assert got == [1, 2, 3, 4, 5, 6, 7, 8]
    # overlapping re-send
    fresh, n_seen = merge_stream_packet(n_seen, 6, [7, 8, 9])
    got.extend(fresh)
    assert got == [1, 2, 3, 4, 5, 6, 7, 8, 9]
    # a gap is left for the authoritative terminal tail
    fresh, n_seen = merge_stream_packet(n_seen, 20, [99])
    assert fresh == [] and n_seen == 9
    # legacy packets without an offset assume contiguity
    fresh, n_seen = merge_stream_packet(n_seen, None, [10, 11])
    got.extend(fresh)
    assert got == [1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11]


async def test_engine_burst_packets_carry_worker_sink_offsets():
    """A speculative engine emits multi-token packets; the worker sink's
    offset formula (n_generated - len(new_tokens)) must describe each
    burst's true position so offset-deduping consumers reassemble the
    exact sequence — including under a simulated duplicate delivery."""
    packets: list[tuple[list[int], int]] = []

    async def sink(new_tokens, n_generated, done):
        packets.append((list(new_tokens), n_generated))

    prompt, max_new = [5, 9, 17, 3], 12
    eng = ServingEngine(SpecFakeBackend(), run_blocking=run_blocking,
                        max_new_tokens_cap=max_new, speculative=True,
                        draft_k=4, drafter=perfect_drafter)
    r = await eng.submit(GenRequest(prompt=prompt, max_new_tokens=max_new),
                         job_id="s1", on_tokens=sink)
    await eng.stop()
    assert r["tokens"] == fake_ref(prompt, max_new)
    assert any(len(toks) > 1 for toks, _ in packets)  # bursts actually flowed
    # the worker sink's offset formula, applied per packet
    offs = [max(0, n_gen - len(toks)) for toks, n_gen in packets]
    n_seen, got = 0, []
    for (toks, _), off in zip(packets, offs):
        fresh, n_seen = merge_stream_packet(n_seen, off, toks)
        got.extend(fresh)
    assert got == r["tokens"]
    # duplicate delivery of every packet (at-least-once bus) still exact
    n_seen, got = 0, []
    for (toks, _), off in zip(packets, offs):
        for _ in range(2):
            fresh, n_seen = merge_stream_packet(n_seen, off, toks)
            got.extend(fresh)
    assert got == r["tokens"]


async def test_resume_tokens_replay_with_speculation():
    """Failover resume on a speculative engine: the resume prefix replays
    at offset 0, speculation continues the tail, and the assembled stream
    equals the uninterrupted sequential run exactly."""
    prompt, max_new = [5, 9, 17, 3], 10
    full = fake_ref(prompt, max_new)
    packets: list[tuple[list[int], int]] = []

    async def sink(new_tokens, n_generated, done):
        packets.append((list(new_tokens), n_generated))

    eng = ServingEngine(SpecFakeBackend(), run_blocking=run_blocking,
                        max_new_tokens_cap=max_new, speculative=True,
                        draft_k=4, drafter=perfect_drafter)
    r = await eng.submit(
        GenRequest(prompt=prompt, max_new_tokens=max_new,
                   resume_tokens=full[:4]),
        job_id="resume1", on_tokens=sink)
    await eng.stop()
    assert r["tokens"] == full
    # a consumer that saw the first worker's stream die after 4 tokens
    # dedupes the replay and ends with the exact sequence
    n_seen, got = 4, list(full[:4])
    for toks, n_gen in packets:
        fresh, n_seen = merge_stream_packet(
            n_seen, max(0, n_gen - len(toks)), toks)
        got.extend(fresh)
    assert got == full


# --------------------------------------------------- CI perf-floor wiring


def test_floor_checker_gates_spec_keys():
    import json
    import sys
    from pathlib import Path

    repo = Path(__file__).resolve().parents[1]
    sys.path.insert(0, str(repo / "tools"))
    try:
        import check_bench_floor as mod
    finally:
        sys.path.pop(0)
    floors = json.loads((repo / "bench_floor.json").read_text())
    base = {"spec_decode_speedup": 1.96, "spec_token_identity": 1,
            "spec_compile_count": 1}
    # healthy values: no spec-key violations (other keys flag missing)
    assert not any("spec" in v for v in mod.check(dict(base), floors))
    for key, bad in [("spec_decode_speedup", 1.0),
                     ("spec_token_identity", 0),
                     ("spec_compile_count", 2)]:
        doc = dict(base)
        doc[key] = bad
        assert any(key in v for v in mod.check(doc, floors)), key
    # a missing identity key is itself a violation (the gate cannot be
    # skipped by dropping the metric)
    doc = dict(base)
    doc.pop("spec_token_identity")
    assert any("spec_token_identity" in v for v in mod.check(doc, floors))


# ---------------------------------------------------------------------------
# capacity surface: beacon key, renderer column, placer preference
# ---------------------------------------------------------------------------


def test_capacity_view_spec_accept_presence_is_the_signal():
    from .test_capacity import _decode_beacon, _mk_view

    clock = [0.0]
    view = _mk_view(clock)
    view.ingest(_decode_beacon(
        "w-spec", occ={"active_sessions": 2, "spec_accept_rate": 0.85},
        kv={"pages_total": 64, "pages_free": 30}))
    view.ingest(_decode_beacon(
        "w-plain", occ={"active_sessions": 1},
        kv={"pages_total": 64, "pages_free": 30}))
    assert view.spec_accept("w-spec") == 0.85
    assert view.spec_accept("w-plain") is None  # key absent = disabled
    assert view.spec_accept("w-gone") is None
    clock[0] += 100.0  # stale beacons read as unmeasured
    assert view.spec_accept("w-spec") is None


def test_render_worker_table_accept_column_degrades():
    from cordum_tpu.obs.capacity import render_worker_table

    lines = render_worker_table({
        "w-spec": {"fresh": True, "serving_role": "mixed",
                   "kv_pages": {"pages_total": 64, "pages_free": 30,
                                "pages_in_use": 34},
                   "occupancy": {"active_sessions": 2, "decode_mean": 1.5,
                                 "spec_accept_rate": 0.85}},
        "w-plain": {"fresh": True, "serving_role": "mixed",
                    "kv_pages": {"pages_total": 64, "pages_free": 64,
                                 "pages_in_use": 0},
                    "occupancy": {"active_sessions": 0, "decode_mean": 0.0}},
    })
    assert lines and "accept" in lines[0]
    spec_row = next(ln for ln in lines if ln.startswith("w-spec"))
    plain_row = next(ln for ln in lines if ln.startswith("w-plain"))
    assert "85%" in spec_row
    assert "85%" not in plain_row  # speculation disabled renders "-"
    # every row carries every column: the renderer never KeyErrors on a
    # worker whose beacon predates the accept field
    assert len(spec_row.split()) == len(plain_row.split())


def test_placer_prefers_draft_enabled_workers_for_speculable():
    from .test_disagg import StubView, hb

    class SpecView(StubView):
        def __init__(self):
            super().__init__()
            self.accept: dict[str, float] = {}

        def spec_accept(self, wid):
            return self.accept.get(wid)

    view = SpecView()
    for w in ("w-spec", "w-plain"):
        view.rates[(w, "llm.prefill")] = 100.0
        view.kv[w] = {"pages_total": 100, "pages_free": 100}
    view.accept["w-spec"] = 0.7
    placer = ServingPlacer(view)
    cands = [hb("w-spec"), hb("w-plain")]
    # speculable sessions: the draft-enabled worker wins every time
    assert all(placer.pick(cands, speculable=True) == "w-spec"
               for _ in range(20))
    # ordinary sessions: both workers share the load (equal rates)
    picks = {placer.pick(cands) for _ in range(20)}
    assert picks == {"w-spec", "w-plain"}
    # preference, not a filter: no draft-enabled worker -> still places
    view.accept.clear()
    assert placer.pick(cands, speculable=True) in ("w-spec", "w-plain")


def test_label_speculable_reaches_placer_via_strategy():
    """The strategy passes the LABEL_SPECULABLE hint through to
    placer.pick — a labeled serving job prefers draft-enabled workers."""
    from cordum_tpu.infra.config import parse_pool_config
    from cordum_tpu.infra.registry import WorkerRegistry
    from cordum_tpu.controlplane.scheduler.strategy import (
        ThroughputAwareStrategy,
    )
    from cordum_tpu.protocol.types import (
        JobRequest,
        LABEL_OP,
        LABEL_SPECULABLE,
    )

    from .test_disagg import StubView, hb

    class SpecView(StubView):
        def __init__(self):
            super().__init__()
            self.accept: dict[str, float] = {}

        def spec_accept(self, wid):
            return self.accept.get(wid)

    view = SpecView()
    for w in ("w-spec", "w-plain"):
        view.rates[(w, "llm.prefill")] = 100.0
        view.kv[w] = {"pages_total": 100, "pages_free": 100}
    view.accept["w-spec"] = 0.9
    reg = WorkerRegistry()
    pc = parse_pool_config({"topics": {"job.tpu.generate": "tpu"},
                            "pools": {"tpu": {}}})
    strat = ThroughputAwareStrategy(reg, pc, capacity=view,
                                    placer=ServingPlacer(view), native=False)
    for w in ("w-spec", "w-plain"):
        reg.update(hb(w))
    req = JobRequest(job_id="spec-job", topic="job.tpu.generate",
                     labels={LABEL_OP: "llm.generate", LABEL_SPECULABLE: "1"})
    assert strat.pick_subject(req) == "worker.w-spec.jobs"
