"""Statebus server: KV over TCP, pub/sub with queue groups, dedupe, AOF
persistence, and a cross-connection control-plane round trip."""
import asyncio
import os

import pytest

from cordum_tpu.infra.statebus import StateBusServer, connect
from cordum_tpu.protocol import subjects as subj
from cordum_tpu.protocol.types import BusPacket, Heartbeat, JobRequest, JobResult


async def start_server(**kw):
    srv = StateBusServer(port=0, **kw)
    await srv.start()
    return srv


async def test_kv_over_tcp():
    srv = await start_server()
    kv, bus, conn = await connect(f"statebus://127.0.0.1:{srv.port}")
    try:
        await kv.set("a", b"1")
        assert await kv.get("a") == b"1"
        assert await kv.setnx("a", b"2") is False
        await kv.hset("h", {"x": b"1"})
        assert await kv.hgetall("h") == {"x": b"1"}
        await kv.zadd("z", "m1", 2.0)
        await kv.zadd("z", "m2", 1.0)
        assert await kv.zrange("z") == ["m2", "m1"]
        await kv.rpush("l", b"a", b"b")
        assert await kv.lrange("l") == [b"a", b"b"]
        await kv.sadd("s", "x", "y")
        assert await kv.smembers("s") == {"x", "y"}
        ver = await kv.version("a")
        assert await kv.commit({"a": ver}, [("set", "a", b"3")]) is True
        assert await kv.commit({"a": ver}, [("set", "a", b"4")]) is False
        assert await kv.get("a") == b"3"
        assert await kv.ping()
    finally:
        await conn.close()
        await srv.stop()


async def test_pubsub_queue_groups_across_connections():
    srv = await start_server()
    kv1, bus1, c1 = await connect(f"statebus://127.0.0.1:{srv.port}")
    kv2, bus2, c2 = await connect(f"statebus://127.0.0.1:{srv.port}")
    got1, got2, fan = [], [], []
    try:
        async def h1(s, p):
            got1.append(p.job_request.job_id)

        async def h2(s, p):
            got2.append(p.job_request.job_id)

        async def hf(s, p):
            fan.append(s)

        await bus1.subscribe("sys.job.submit", h1, queue="g")
        await bus2.subscribe("sys.job.submit", h2, queue="g")
        await bus2.subscribe("sys.job.>", hf)
        for i in range(6):
            await bus1.publish(subj.SUBMIT, BusPacket.wrap(JobRequest(job_id=f"j{i}", topic="t")))
        await asyncio.sleep(0.2)
        assert len(got1) + len(got2) == 6  # queue group: each message once
        assert got1 and got2  # round-robin reached both connections
        assert len(fan) == 6  # plain sub fans out
    finally:
        await c1.close()
        await c2.close()
        await srv.stop()


async def test_server_side_dedupe():
    srv = await start_server()
    kv, bus, conn = await connect(f"statebus://127.0.0.1:{srv.port}")
    got = []
    try:
        async def h(s, p):
            got.append(p.job_request.job_id)

        await bus.subscribe("sys.job.submit", h, queue="g")
        req = JobRequest(job_id="same", topic="t")
        await bus.publish(subj.SUBMIT, BusPacket.wrap(req))
        await bus.publish(subj.SUBMIT, BusPacket.wrap(req))
        await asyncio.sleep(0.15)
        assert got == ["same"]
    finally:
        await conn.close()
        await srv.stop()


async def test_aof_persistence(tmp_path):
    aof = str(tmp_path / "state.aof")
    srv = await start_server(aof_path=aof)
    kv, bus, conn = await connect(f"statebus://127.0.0.1:{srv.port}")
    await kv.set("persisted", b"yes")
    await kv.hset("job:meta:j1", {"state": b"RUNNING"})
    await kv.zadd("job:index:RUNNING", "j1", 123.0)
    await conn.close()
    await srv.stop()
    assert os.path.getsize(aof) > 0
    # crash-restart: a new server replays the log
    srv2 = StateBusServer(port=0, aof_path=aof)
    await srv2.start()
    kv2, _, conn2 = await connect(f"statebus://127.0.0.1:{srv2.port}")
    try:
        assert await kv2.get("persisted") == b"yes"
        assert await kv2.hgetall("job:meta:j1") == {"state": b"RUNNING"}
        assert await kv2.zrange("job:index:RUNNING") == ["j1"]
    finally:
        await conn2.close()
        await srv2.stop()


async def test_client_reconnects_and_resubscribes(tmp_path):
    """Kill the statebus mid-flow: in-flight calls fail, but the client
    reconnects with backoff, re-issues its subscriptions, and the stack
    recovers without a process restart (reference NATS: infinite reconnect,
    nats.go:59)."""
    aof = str(tmp_path / "state.aof")
    srv = await start_server(aof_path=aof)
    port = srv.port
    kv, bus, conn = await connect(f"statebus://127.0.0.1:{port}")
    got = []

    async def h(s, p):
        got.append(p.job_request.job_id)

    try:
        await bus.subscribe("sys.job.submit", h, queue="g")
        await kv.set("before", b"1")
        await bus.publish(subj.SUBMIT, BusPacket.wrap(JobRequest(job_id="j0", topic="t")))
        await asyncio.sleep(0.1)
        assert got == ["j0"]

        # hard-kill the server
        await srv.stop()
        await asyncio.sleep(0.05)
        with pytest.raises(ConnectionError):
            await conn.call("set", "during", b"x", timeout_s=0.3)  # fails while down (bounded)
        # restart on the same port with the same AOF
        srv2 = StateBusServer(port=port, aof_path=aof)
        await srv2.start()
        # next calls ride the reconnect (call() waits for _connected)
        assert await kv.get("before") == b"1"
        assert conn.reconnect_count == 1
        # subscription survived the blip — no re-subscribe by the app
        await bus.publish(subj.SUBMIT, BusPacket.wrap(JobRequest(job_id="j1", topic="t")))
        await asyncio.sleep(0.15)
        assert got == ["j0", "j1"]
        await srv2.stop()
    finally:
        await conn.close()


async def test_reconnect_waits_with_backoff(tmp_path):
    """A call issued while the server is still down blocks until the server
    returns (within its timeout) instead of erroring permanently."""
    aof = str(tmp_path / "state.aof")
    srv = await start_server(aof_path=aof)
    port = srv.port
    kv, bus, conn = await connect(f"statebus://127.0.0.1:{port}")
    try:
        await kv.set("k", b"v")
        await srv.stop()
        await asyncio.sleep(0.05)

        async def bring_back():
            await asyncio.sleep(0.4)
            s2 = StateBusServer(port=port, aof_path=aof)
            await s2.start()
            return s2

        task = asyncio.ensure_future(bring_back())
        # issued while down; succeeds once the reconnect loop wins
        assert await kv.get("k") == b"v"
        srv2 = await task
        await srv2.stop()
    finally:
        await conn.close()


async def test_control_plane_over_statebus():
    """Scheduler + worker in 'separate processes' (separate connections)
    driving a job end-to-end through the TCP statebus."""
    from cordum_tpu.controlplane.safetykernel.kernel import SafetyKernel
    from cordum_tpu.controlplane.scheduler.engine import Engine
    from cordum_tpu.controlplane.scheduler.safety_client import SafetyClient
    from cordum_tpu.controlplane.scheduler.strategy import LeastLoadedStrategy
    from cordum_tpu.infra.config import parse_pool_config
    from cordum_tpu.infra.jobstore import JobStore
    from cordum_tpu.infra.memstore import MemoryStore
    from cordum_tpu.infra.registry import WorkerRegistry
    from cordum_tpu.worker.runtime import Worker

    srv = await start_server()
    url = f"statebus://127.0.0.1:{srv.port}"
    skv, sbus, sconn = await connect(url)   # scheduler process
    wkv, wbus, wconn = await connect(url)   # worker process
    gkv, gbus, gconn = await connect(url)   # gateway-role process
    try:
        js = JobStore(skv)
        reg = WorkerRegistry()
        pc = parse_pool_config({"topics": {"job.work": "p"}, "pools": {"p": {}}})
        eng = Engine(bus=sbus, job_store=js, safety=SafetyClient(SafetyKernel(policy_doc={}).check),
                     strategy=LeastLoadedStrategy(reg, pc), registry=reg)
        await eng.start()

        w = Worker(bus=wbus, store=MemoryStore(wkv), worker_id="w1", pool="p",
                   topics=["job.work"], heartbeat_interval_s=999)

        async def handler(ctx):
            return {"echo": ctx.payload}

        w.register("job.work", handler)
        await w.start()
        await asyncio.sleep(0.1)

        gm = MemoryStore(gkv)
        ptr = await gm.put_context("j1", {"hello": "tcp"})
        await gbus.publish(subj.SUBMIT, BusPacket.wrap(
            JobRequest(job_id="j1", topic="job.work", context_ptr=ptr)))
        for _ in range(100):
            await asyncio.sleep(0.02)
            if await js.get_state("j1") == "SUCCEEDED":
                break
        assert await js.get_state("j1") == "SUCCEEDED"
        res = await gm.get_result("j1")
        assert res == {"echo": {"hello": "tcp"}}
        await w.stop()
        await eng.stop()
    finally:
        await sconn.close()
        await wconn.close()
        await gconn.close()
        await srv.stop()
