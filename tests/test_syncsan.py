"""Runtime sync sanitizer (CORDUM_SYNC_SANITIZER=1): detects the interleave
races CL008 flags statically — a seeded lost update is reported, the locked
fix is silent, and instrumentation is a strict no-op when disabled."""
from __future__ import annotations

import asyncio

from cordum_tpu.infra import syncsan


class Racy:
    """Fixture with the exact annotation grammar syncsan instruments."""

    def __init__(self):
        self._lock = asyncio.Lock()
        self.counter = 0  # cordum: guarded-by(_lock)

    async def bump_unlocked(self):
        cur = self.counter
        await asyncio.sleep(0)
        self.counter = cur + 1

    async def bump_locked(self):
        async with self._lock:
            cur = self.counter
            await asyncio.sleep(0)
            self.counter = cur + 1


class Plain:
    def __init__(self):
        self.counter = 0


def test_guarded_attrs_parses_annotation_grammar():
    assert syncsan.guarded_attrs(Racy) == {"counter": "_lock"}
    assert syncsan.guarded_attrs(Plain) == {}


def test_instrument_is_noop_when_disabled(monkeypatch):
    monkeypatch.delenv(syncsan.ENV_VAR, raising=False)

    class Off:
        def __init__(self):
            self._lock = asyncio.Lock()
            self.x = 0  # cordum: guarded-by(_lock)

    cls = syncsan.instrument(Off)
    assert cls is Off
    assert "x" not in Off.__dict__  # no descriptor installed
    obj = Off()
    assert isinstance(obj._lock, asyncio.Lock)  # not wrapped either


def _instrumented(monkeypatch):
    monkeypatch.setenv(syncsan.ENV_VAR, "1")
    cls = syncsan.instrument(Racy)  # idempotent: descriptors re-installed
    assert cls is Racy
    return Racy


async def test_detects_seeded_lost_update(monkeypatch):
    cls = _instrumented(monkeypatch)
    obj = cls()
    syncsan.reset()
    await asyncio.gather(obj.bump_unlocked(), obj.bump_unlocked())
    reps = syncsan.reports()
    syncsan.reset()
    assert any(r.kind == "lost-update" for r in reps), reps
    rep = next(r for r in reps if r.kind == "lost-update")
    assert rep.cls == "Racy" and rep.attr == "counter" and rep.lock == "_lock"
    # and the race really did lose an update
    assert obj.counter == 1


async def test_locked_fix_is_silent(monkeypatch):
    cls = _instrumented(monkeypatch)
    obj = cls()
    syncsan.reset()
    await asyncio.gather(obj.bump_locked(), obj.bump_locked())
    reps = syncsan.reports()
    syncsan.reset()
    assert reps == []
    assert obj.counter == 2


async def test_lock_is_wrapped_for_ownership(monkeypatch):
    cls = _instrumented(monkeypatch)
    obj = cls()
    syncsan.reset()
    assert isinstance(obj._lock, syncsan.TrackedLock)
    assert not obj._lock.held_by_current()
    async with obj._lock:
        assert obj._lock.held_by_current()
    assert not obj._lock.held_by_current()
    syncsan.reset()


async def test_reports_write_under_foreign_lock(monkeypatch):
    cls = _instrumented(monkeypatch)
    obj = cls()
    syncsan.reset()
    entered = asyncio.Event()
    release = asyncio.Event()

    async def holder():
        async with obj._lock:
            entered.set()
            await release.wait()

    async def intruder():
        await entered.wait()
        obj.counter = 99  # unlocked write while holder owns the lock
        release.set()

    await asyncio.gather(holder(), intruder())
    reps = syncsan.reports()
    syncsan.reset()
    assert any(r.kind == "write-under-foreign-lock" for r in reps), reps


async def test_single_task_rmw_is_silent(monkeypatch):
    cls = _instrumented(monkeypatch)
    obj = cls()
    syncsan.reset()
    for _ in range(5):
        await obj.bump_unlocked()  # sequential: no interleave, no report
    reps = syncsan.reports()
    syncsan.reset()
    assert reps == []
    assert obj.counter == 5
