"""Checkpointed training jobs: run, resume-from-checkpoint, cancel,
profiler hook, and the e2e train op through the control plane."""
import asyncio
import os

import pytest

from cordum_tpu.worker.training import TrainRunner, profile_trace


def test_train_runs_and_loss_drops(tmp_path):
    runner = TrainRunner(ckpt_root=str(tmp_path))
    out = runner.train({"model": "llama-tiny", "steps": 4, "batch": 4, "seq": 16,
                        "fixed_batch": True})
    assert out["completed"] and out["steps_done"] == 4
    assert out["final_loss"] < out["loss_first"]
    assert not out["checkpointed"]


def test_train_checkpoint_resume(tmp_path):
    runner = TrainRunner(ckpt_root=str(tmp_path))
    payload = {"model": "llama-tiny", "steps": 6, "batch": 4, "seq": 16,
               "checkpoint_every": 2, "run_name": "resume-test"}
    # first attempt is cancelled after 3 steps (simulated preemption)
    calls = {"n": 0}

    def cancel_after_3():
        calls["n"] += 1
        return calls["n"] > 3

    out1 = runner.train(payload, cancelled=cancel_after_3)
    assert not out1["completed"]
    assert out1["steps_done"] == 3
    # re-dispatch resumes from the last checkpoint (step 2), not from zero
    out2 = runner.train(payload)
    assert out2["resumed_from"] == 2
    assert out2["completed"] and out2["steps_done"] == 6


def test_train_pipeline_family(tmp_path):
    runner = TrainRunner(ckpt_root=str(tmp_path))
    out = runner.train({"model": "pipeline", "steps": 2, "batch": 8, "seq": 12,
                        "mesh": {"pp": 2}})
    assert out["completed"]
    assert out["mesh"]["pp"] == 2


def test_profile_trace(tmp_path):
    import jax
    import jax.numpy as jnp

    fn = jax.jit(lambda x: (x @ x.T).sum())
    x = jnp.ones((64, 64))
    out, trace_dir = profile_trace(fn, x, trace_dir=str(tmp_path / "trace"))
    assert float(out) == 64 * 64 * 64
    # profiler wrote something
    files = [os.path.join(dp, f) for dp, _, fs in os.walk(trace_dir) for f in fs]
    assert files, "no trace files written"


async def test_train_op_end_to_end(tmp_path):
    from tests.test_worker import make_stack, settle
    from cordum_tpu.worker.handlers import TPUCompute, make_tpu_handlers
    from cordum_tpu.worker.runtime import Worker
    from cordum_tpu.protocol import subjects as subj
    from cordum_tpu.protocol.types import BusPacket, JobRequest

    os.environ["CORDUM_CKPT_DIR"] = str(tmp_path)
    kv, bus, js, ms, eng = make_stack()
    await eng.start()
    w = Worker(bus=bus, store=ms, worker_id="w-train", pool="tpu",
               topics=["job.tpu.>"], capabilities=["tpu"], heartbeat_interval_s=999)
    from cordum_tpu.models.embedder import EmbedderConfig

    w.register_default(make_tpu_handlers(TPUCompute(embedder_cfg=EmbedderConfig(n_layers=1, d_model=64, max_len=16))))
    await w.start()
    await settle(bus)
    ptr = await ms.put_context("j-train", {"op": "train", "model": "llama-tiny",
                                           "steps": 3, "batch": 4, "seq": 16})
    await bus.publish(subj.SUBMIT, BusPacket.wrap(
        JobRequest(job_id="j-train", topic="job.tpu.train", context_ptr=ptr)))
    for _ in range(400):
        await settle(bus, rounds=2)
        if await js.get_state("j-train") == "SUCCEEDED":
            break
    assert await js.get_state("j-train") == "SUCCEEDED"
    res = await ms.get_result("j-train")
    assert res["completed"] and res["steps_done"] == 3
    # progress events flowed
    evs = await js.events("j-train")
    assert any(e.get("event") == "progress" for e in evs)
    await w.stop(); await eng.stop()
