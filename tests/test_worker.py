"""Worker runtime + end-to-end integration slice: gateway-role submit →
scheduler → TPU worker executing JAX ops → result pointer → terminal state.
This is the loopback equivalent of the reference's integration tests
(scheduler/integration_test.go) plus real XLA compute."""
import asyncio

import pytest

from cordum_tpu.controlplane.safetykernel.kernel import SafetyKernel
from cordum_tpu.controlplane.scheduler.engine import Engine
from cordum_tpu.controlplane.scheduler.safety_client import SafetyClient
from cordum_tpu.controlplane.scheduler.strategy import LeastLoadedStrategy
from cordum_tpu.infra.bus import LoopbackBus
from cordum_tpu.infra.config import parse_pool_config
from cordum_tpu.infra.jobstore import JobStore
from cordum_tpu.infra.kv import MemoryKV
from cordum_tpu.infra.memstore import MemoryStore
from cordum_tpu.infra.registry import WorkerRegistry
from cordum_tpu.protocol import subjects as subj
from cordum_tpu.protocol.types import BusPacket, JobCancel, JobRequest
from cordum_tpu.worker.handlers import TPUCompute, attach_default_tpu_worker
from cordum_tpu.worker.runtime import JobContext, Worker


async def settle(bus, rounds=6):
    for _ in range(rounds):
        await bus.drain()
        await asyncio.sleep(0.02)


def make_stack(policy_doc=None, pool_doc=None):
    kv = MemoryKV()
    bus = LoopbackBus()
    js = JobStore(kv)
    ms = MemoryStore(kv)
    kernel = SafetyKernel(policy_doc=policy_doc or {})
    reg = WorkerRegistry()
    pc = parse_pool_config(
        pool_doc or {"topics": {"job.default": "default", "job.tpu.>": "tpu"},
                     "pools": {"default": {}, "tpu": {"requires": ["tpu"]}}}
    )
    eng = Engine(bus=bus, job_store=js, safety=SafetyClient(kernel.check),
                 strategy=LeastLoadedStrategy(reg, pc), registry=reg)
    return kv, bus, js, ms, eng


async def test_worker_echo_roundtrip():
    kv, bus, js, ms, eng = make_stack()
    await eng.start()
    w = Worker(bus=bus, store=ms, worker_id="w1", pool="default",
               topics=["job.default"], capabilities=["echo"], heartbeat_interval_s=999)

    async def echo(ctx: JobContext):
        return {"echo": ctx.payload}

    w.register("job.default", echo)
    await w.start()
    await settle(bus)

    ptr = await ms.put_context("j1", {"msg": "hi"})
    await bus.publish(subj.SUBMIT, BusPacket.wrap(JobRequest(job_id="j1", topic="job.default", context_ptr=ptr)))
    await settle(bus)
    assert await js.get_state("j1") == "SUCCEEDED"
    res = await ms.get_result("j1")
    assert res == {"echo": {"msg": "hi"}}
    meta = await js.get_meta("j1")
    assert meta["worker_id"] == "w1"
    assert meta["dispatch_subject"] == "worker.w1.jobs"
    await w.stop()
    await eng.stop()


async def test_blocking_sync_handler_keeps_heartbeats_flowing():
    """A plain-def handler doing blocking work is dispatched to the executor
    by the runtime, so heartbeats keep flowing while it runs (VERDICT weak #5:
    previously a blocking handler silently stopped heartbeats)."""
    import time as _time

    kv, bus, js, ms, eng = make_stack()
    await eng.start()
    w = Worker(bus=bus, store=ms, worker_id="w1", pool="default",
               topics=["job.default"], heartbeat_interval_s=0.05)
    beats = []

    async def hb_tap(subject, pkt):
        if pkt.heartbeat and pkt.heartbeat.worker_id == "w1":
            beats.append((asyncio.get_running_loop().time(), pkt.heartbeat.active_jobs))

    await bus.subscribe(subj.HEARTBEAT, hb_tap)

    def blocking(ctx: JobContext):  # plain def: blocks its thread, not the loop
        _time.sleep(0.6)
        return {"ok": True}

    w.register("job.default", blocking)
    await w.start()
    await settle(bus)
    n0 = len(beats)
    await bus.publish(subj.SUBMIT, BusPacket.wrap(JobRequest(job_id="jb", topic="job.default")))
    # while the job blocks its executor thread, the loop must keep beating
    for _ in range(12):
        await bus.drain()
        await asyncio.sleep(0.06)
    assert await js.get_state("jb") == "SUCCEEDED"
    assert await ms.get_result("jb") == {"ok": True}
    during = len(beats) - n0
    assert during >= 5, f"heartbeats stalled during blocking handler ({during})"
    assert any(active > 0 for _, active in beats), "no heartbeat saw the active job"
    await w.stop()
    await eng.stop()


async def test_worker_failure_reported():
    kv, bus, js, ms, eng = make_stack()
    await eng.start()
    w = Worker(bus=bus, store=ms, worker_id="w1", pool="default",
               topics=["job.default"], heartbeat_interval_s=999)

    async def boom(ctx):
        raise ValueError("bad payload")

    w.register("job.default", boom)
    await w.start()
    await settle(bus)
    await bus.publish(subj.SUBMIT, BusPacket.wrap(JobRequest(job_id="j1", topic="job.default")))
    await settle(bus)
    meta = await js.get_meta("j1")
    assert meta["state"] == "FAILED"
    assert meta["error_code"] == "ValueError"
    assert "bad payload" in meta["error_message"]
    dlq = [p for s, p in bus.published if s == subj.DLQ]
    assert dlq
    await w.stop(); await eng.stop()


async def test_worker_no_handler_fails_cleanly():
    kv, bus, js, ms, eng = make_stack()
    await eng.start()
    w = Worker(bus=bus, store=ms, worker_id="w1", pool="default",
               topics=["job.default"], heartbeat_interval_s=999)
    await w.start()
    await settle(bus)
    await bus.publish(subj.SUBMIT, BusPacket.wrap(JobRequest(job_id="j1", topic="job.default")))
    await settle(bus)
    assert (await js.get_meta("j1"))["state"] == "FAILED"
    await w.stop(); await eng.stop()


async def test_worker_cancel_inflight():
    kv, bus, js, ms, eng = make_stack()
    await eng.start()
    w = Worker(bus=bus, store=ms, worker_id="w1", pool="default",
               topics=["job.default"], heartbeat_interval_s=999)
    started = asyncio.Event()

    async def slow(ctx: JobContext):
        started.set()
        for _ in range(200):
            ctx.check_cancelled()
            await asyncio.sleep(0.01)
        return {"done": True}

    w.register("job.default", slow)
    await w.start()
    await settle(bus)
    await bus.publish(subj.SUBMIT, BusPacket.wrap(JobRequest(job_id="j1", topic="job.default")))
    await asyncio.wait_for(started.wait(), 5)
    await bus.publish(subj.CANCEL, BusPacket.wrap(JobCancel(job_id="j1", reason="test")))
    await settle(bus, rounds=12)
    # worker reported CANCELLED; store shows cancelled (scheduler cancel or result)
    assert (await js.get_meta("j1"))["state"] == "CANCELLED"
    await w.stop(); await eng.stop()


async def test_worker_redelivery_republishes_cached_result():
    """At-least-once: a redelivered completed job must republish its result
    without re-running the handler (reference worker result cache)."""
    kv, bus, js, ms, eng = make_stack()
    await eng.start()
    w = Worker(bus=bus, store=ms, worker_id="w1", pool="default",
               topics=["job.default"], heartbeat_interval_s=999)
    runs = []

    async def handler(ctx):
        runs.append(ctx.request.job_id)
        return {"n": len(runs)}

    w.register("job.default", handler)
    await w.start()
    await settle(bus)
    await bus.publish(subj.SUBMIT, BusPacket.wrap(JobRequest(job_id="j1", topic="job.default")))
    await settle(bus)
    assert runs == ["j1"]
    # deliver the job packet again straight to the worker (simulated
    # redelivery; distinct bus msg-id so dedupe doesn't hide it)
    req = JobRequest(job_id="j1", topic="job.default", labels={"cordum.bus_msg_id": "redeliver"})
    await bus.publish("worker.w1.jobs", BusPacket.wrap(req))
    await settle(bus)
    assert runs == ["j1"]  # handler NOT re-run
    # and the result was republished on the bus
    results = [p for s, p in bus.published if s == subj.RESULT and p.job_result.job_id == "j1"]
    assert len(results) >= 2
    await w.stop(); await eng.stop()


async def test_worker_heartbeat_telemetry_flows_to_registry():
    kv, bus, js, ms, eng = make_stack()
    await eng.start()
    w = Worker(bus=bus, store=ms, worker_id="w-tpu", pool="tpu",
               capabilities=["tpu"], heartbeat_interval_s=999)
    await w.start()
    await settle(bus)
    hb = eng.registry.get("w-tpu")
    assert hb is not None
    assert hb.chip_count == 8  # virtual CPU devices
    assert hb.devices_healthy


async def test_worker_progress_events():
    kv, bus, js, ms, eng = make_stack()
    await eng.start()
    w = Worker(bus=bus, store=ms, worker_id="w1", pool="default",
               topics=["job.default"], heartbeat_interval_s=999)

    async def stepped(ctx: JobContext):
        await ctx.progress(50, "halfway")
        return {"ok": True}

    w.register("job.default", stepped)
    await w.start()
    await settle(bus)
    await bus.publish(subj.SUBMIT, BusPacket.wrap(JobRequest(job_id="j1", topic="job.default")))
    await settle(bus)
    evs = await js.events("j1")
    assert any(e.get("event") == "progress" and e.get("percent") == 50 for e in evs)


# ---------------------------------------------------------------- TPU ops e2e

@pytest.fixture(scope="module")
def compute():
    from cordum_tpu.models.embedder import EmbedderConfig

    return TPUCompute(tp=1, embedder_cfg=EmbedderConfig(n_layers=2, d_model=128, max_len=32))


async def test_e2e_tpu_ops(compute):
    """One worker serving echo/matmul/embed/infer ops end-to-end."""
    kv, bus, js, ms, eng = make_stack()
    await eng.start()
    w = Worker(bus=bus, store=ms, worker_id="w-tpu", pool="tpu",
               topics=["job.tpu.>"], capabilities=["tpu"], heartbeat_interval_s=999)
    from cordum_tpu.worker.handlers import make_tpu_handlers

    w.register_default(make_tpu_handlers(compute))
    await w.start()
    await settle(bus)

    jobs = {
        "j-echo": {"op": "echo", "x": 1},
        "j-matmul": {"op": "matmul", "b": 2, "n": 64, "k": 64, "m": 64},
        "j-embed": {"op": "embed", "texts": ["hello tpu", "goodbye"]},
        "j-infer": {"op": "infer", "tokens": [[1, 2, 3], [4, 5]]},
    }
    for jid, payload in jobs.items():
        ptr = await ms.put_context(jid, payload)
        await bus.publish(subj.SUBMIT, BusPacket.wrap(
            JobRequest(job_id=jid, topic="job.tpu.ops", context_ptr=ptr)))
    for _ in range(60):
        await settle(bus, rounds=2)
        states = [await js.get_state(j) for j in jobs]
        if all(s == "SUCCEEDED" for s in states):
            break
    states = {j: await js.get_state(j) for j in jobs}
    assert all(s == "SUCCEEDED" for s in states.values()), states

    mm = await ms.get_result("j-matmul")
    assert mm["shape"] == [2, 64, 64] and mm["flops"] > 0
    embeds = await ms.get_result("j-embed")
    assert embeds["dim"] == 128 and len(embeds["embeddings"]) == 2
    inf = await ms.get_result("j-infer")
    assert len(inf["next_tokens"]) == 2
    await w.stop(); await eng.stop()


async def test_matmul_rectangular_shapes(compute):
    """k != m must not break the fori_loop carry (review regression)."""
    out = compute.matmul(2, 32, 48, 96, iters=3)
    assert out["shape"] == [2, 32, 96]
    assert out["flops"] == 2.0 * 2 * 32 * 48 * 96 * 7


async def test_result_status_not_deduped():
    """A terminal result must survive dedupe after a RUNNING hint (review
    regression)."""
    from cordum_tpu.protocol.types import JobResult

    kv, bus, js, ms, eng = make_stack()
    await eng.start()
    reg_hb = eng.registry
    from cordum_tpu.protocol.types import Heartbeat

    reg_hb.update(Heartbeat(worker_id="w1", pool="default", max_parallel_jobs=4))
    await bus.publish(subj.SUBMIT, BusPacket.wrap(JobRequest(job_id="j1", topic="job.default")))
    await settle(bus)
    await bus.publish(subj.RESULT, BusPacket.wrap(JobResult(job_id="j1", status="RUNNING", worker_id="w1")))
    await settle(bus)
    await bus.publish(subj.RESULT, BusPacket.wrap(JobResult(job_id="j1", status="SUCCEEDED", worker_id="w1")))
    await settle(bus)
    assert await js.get_state("j1") == "SUCCEEDED"
    await eng.stop()


def test_topology_requirement_rejects_unknown_topology():
    from cordum_tpu.controlplane.scheduler.strategy import worker_satisfies
    from cordum_tpu.protocol.types import Heartbeat

    hb = Heartbeat(worker_id="w", capabilities=["tpu"], chip_count=8, slice_topology="")
    assert not worker_satisfies(hb, None, ["topology:2x2x2"])
    hb2 = Heartbeat(worker_id="w", capabilities=["tpu"], chip_count=8, slice_topology="2x2x2")
    assert worker_satisfies(hb2, None, ["topology:2x2x2"])


async def test_e2e_bad_op_fails(compute):
    kv, bus, js, ms, eng = make_stack()
    await eng.start()
    w = Worker(bus=bus, store=ms, worker_id="w-tpu", pool="tpu",
               topics=["job.tpu.>"], capabilities=["tpu"], heartbeat_interval_s=999)
    from cordum_tpu.worker.handlers import make_tpu_handlers

    w.register_default(make_tpu_handlers(compute))
    await w.start()
    await settle(bus)
    ptr = await ms.put_context("j-bad", {"op": "nonsense"})
    await bus.publish(subj.SUBMIT, BusPacket.wrap(JobRequest(job_id="j-bad", topic="job.tpu.ops", context_ptr=ptr)))
    await settle(bus, rounds=10)
    meta = await js.get_meta("j-bad")
    assert meta["state"] == "FAILED" and "nonsense" in meta["error_message"]
    await w.stop(); await eng.stop()
