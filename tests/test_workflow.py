"""Workflow engine tests: expression eval, templates, store, the step state
machine (DAG, conditions, delay/notify/approval, fan-out with max_parallel,
retries, rerun), and service integration with the scheduler+worker."""
import asyncio
import json

import pytest

from cordum_tpu.infra.bus import LoopbackBus
from cordum_tpu.infra.jobstore import JobStore
from cordum_tpu.infra.kv import MemoryKV
from cordum_tpu.infra.memstore import MemoryStore
from cordum_tpu.infra.schemareg import SchemaRegistry
from cordum_tpu.protocol import subjects as subj
from cordum_tpu.protocol.types import BusPacket, JobResult
from cordum_tpu.workflow import models as M
from cordum_tpu.workflow.engine import Engine, make_job_id, split_job_id
from cordum_tpu.workflow.eval import evaluate, expand_templates, resolve_path, set_path, truthy
from cordum_tpu.workflow.models import Workflow
from cordum_tpu.workflow.store import WorkflowStore


# ---------------------------------------------------------------- eval

def test_eval_literals_and_paths():
    scope = {"input": {"n": 3, "name": "x"}, "steps": {"a": {"out": [1, 2]}}}
    assert evaluate("input.n", scope) == 3
    assert evaluate("steps.a.out.1", scope) == 2
    assert evaluate("input.missing", scope) is None
    assert evaluate("'hello'", scope) == "hello"
    assert evaluate("42", scope) == 42
    assert evaluate("true", scope) is True


def test_eval_comparisons_and_negation():
    scope = {"input": {"n": 3, "s": "ok"}}
    assert evaluate("input.n == 3", scope) is True
    assert evaluate("input.n != 3", scope) is False
    assert evaluate("input.n > 2", scope) is True
    assert evaluate("input.n <= 2", scope) is False
    assert evaluate("input.s == 'ok'", scope) is True
    assert evaluate("!input.missing", scope) is True
    assert evaluate("!input.n", scope) is False


def test_eval_functions():
    scope = {"steps": {"a": {"items": [5, 6, 7]}}}
    assert evaluate("length(steps.a.items)", scope) == 3
    assert evaluate("first(steps.a.items)", scope) == 5
    assert evaluate("length(steps.a.items) == 3", scope) is True
    assert evaluate("length(steps.missing)", scope) == 0


def test_truthy():
    assert truthy(1) and truthy("x") and truthy([0]) and truthy({"a": 1})
    assert not truthy(0) and not truthy("") and not truthy([]) and not truthy(None)
    assert not truthy("false")


def test_templates():
    scope = {"input": {"name": "world", "n": 2}, "steps": {"a": {"v": [1, 2]}}}
    assert expand_templates("${input.name}", scope) == "world"
    assert expand_templates("${steps.a.v}", scope) == [1, 2]  # type-preserving
    assert expand_templates("hello ${input.name}!", scope) == "hello world!"
    assert expand_templates({"x": "${input.n}", "y": ["${input.name}"]}, scope) == {
        "x": 2, "y": ["world"]
    }
    assert expand_templates("a=${steps.a.v}", scope) == "a=[1, 2]"


def test_set_path():
    d = {}
    set_path(d, "a.b.c", 5)
    assert d == {"a": {"b": {"c": 5}}}


def test_job_id_roundtrip():
    jid = make_job_id("run-1", "step#3", 2)
    assert split_job_id(jid) == ("run-1", "step#3", 2)
    with pytest.raises(ValueError):
        split_job_id("plain-job-id")


# ---------------------------------------------------------------- harness

def wf_doc(steps, **kw):
    return {"id": kw.get("id", "wf1"), "name": "test", "steps": steps, **kw}


class Harness:
    def __init__(self, kv=None):
        self.kv = kv or MemoryKV()
        self.bus = LoopbackBus(sync=True)
        self.store = WorkflowStore(self.kv)
        self.mem = MemoryStore(self.kv)
        self.schemas = SchemaRegistry(self.kv)
        self.engine = Engine(store=self.store, bus=self.bus, mem=self.mem, schemas=self.schemas)
        self.dispatched: list = []

    async def setup(self, doc):
        wf = Workflow.from_dict(doc)
        assert wf.validate() == []
        await self.store.put_workflow(wf)

        async def capture(subject, pkt):
            if pkt.job_request:
                self.dispatched.append(pkt.job_request)

        await self.bus.subscribe(subj.SUBMIT, capture)
        return wf

    async def succeed(self, job_id, output=None):
        ptr = ""
        if output is not None:
            ptr = await self.mem.put_result(job_id, output)
        await self.engine.handle_job_result(
            JobResult(job_id=job_id, status="SUCCEEDED", result_ptr=ptr, worker_id="w")
        )

    async def fail(self, job_id, msg="boom"):
        await self.engine.handle_job_result(
            JobResult(job_id=job_id, status="FAILED", error_message=msg, worker_id="w")
        )


# ---------------------------------------------------------------- engine

async def test_linear_dag_dataflow():
    h = Harness()
    await h.setup(wf_doc({
        "a": {"topic": "job.t", "input": {"v": "${input.x}"}},
        "b": {"topic": "job.t", "depends_on": ["a"], "input": {"prev": "${steps.a.doubled}"}},
    }))
    run = await h.engine.start_run("wf1", {"x": 21})
    assert run.status == M.RUNNING
    assert len(h.dispatched) == 1
    ctx = await h.mem.get_pointer(h.dispatched[0].context_ptr)
    assert ctx == {"v": 21}
    await h.succeed(h.dispatched[0].job_id, {"doubled": 42})
    assert len(h.dispatched) == 2
    ctx_b = await h.mem.get_pointer(h.dispatched[1].context_ptr)
    assert ctx_b == {"prev": 42}
    await h.succeed(h.dispatched[1].job_id, {"ok": True})
    run = await h.store.get_run(run.run_id)
    assert run.status == M.SUCCEEDED
    assert run.context["steps"]["b"] == {"ok": True}


async def test_parallel_independent_steps():
    h = Harness()
    await h.setup(wf_doc({
        "a": {"topic": "job.t"},
        "b": {"topic": "job.t"},
        "c": {"topic": "job.t", "depends_on": ["a", "b"]},
    }))
    run = await h.engine.start_run("wf1", {})
    assert len(h.dispatched) == 2  # a and b dispatch in the same wave
    await h.succeed(h.dispatched[0].job_id, {})
    assert len(h.dispatched) == 2  # c still blocked on b
    await h.succeed(h.dispatched[1].job_id, {})
    assert len(h.dispatched) == 3


async def test_condition_gate_skips_and_dependents_run():
    h = Harness()
    await h.setup(wf_doc({
        "a": {"topic": "job.t", "condition": "input.enabled"},
        "b": {"topic": "job.t", "depends_on": ["a"]},
    }))
    run = await h.engine.start_run("wf1", {"enabled": False})
    run = await h.store.get_run(run.run_id)
    assert run.steps["a"].status == M.SKIPPED
    # SKIPPED counts as satisfied → b dispatched
    assert len(h.dispatched) == 1 and h.dispatched[0].job_id.split(":")[1].startswith("b")


async def test_condition_step_records_value():
    h = Harness()
    await h.setup(wf_doc({
        "check": {"type": "condition", "condition": "input.n > 2"},
        "then": {"topic": "job.t", "depends_on": ["check"], "condition": "steps.check.value"},
    }))
    run = await h.engine.start_run("wf1", {"n": 5})
    run = await h.store.get_run(run.run_id)
    assert run.context["steps"]["check"] == {"value": True}
    assert len(h.dispatched) == 1
    run2 = await h.engine.start_run("wf1", {"n": 1})
    run2 = await h.store.get_run(run2.run_id)
    assert run2.steps["then"].status == M.SKIPPED
    assert run2.status == M.SUCCEEDED


async def test_notify_step_emits_alert():
    h = Harness()
    alerts = []

    async def tap(subject, pkt):
        alerts.append(pkt.system_alert)

    await h.bus.subscribe(subj.WORKFLOW_EVENT, tap)
    await h.setup(wf_doc({
        "n": {"type": "notify", "notify_message": "run for ${input.who}", "notify_severity": "warning"},
    }))
    run = await h.engine.start_run("wf1", {"who": "ops"})
    assert alerts and alerts[0].message == "run for ops"
    assert alerts[0].severity == "warning"
    run = await h.store.get_run(run.run_id)
    assert run.status == M.SUCCEEDED


async def test_delay_step_parks_and_resumes():
    h = Harness()
    await h.setup(wf_doc({
        "wait": {"type": "delay", "delay_sec": 0.05},
        "after": {"topic": "job.t", "depends_on": ["wait"]},
    }))
    run = await h.engine.start_run("wf1", {})
    run = await h.store.get_run(run.run_id)
    assert run.steps["wait"].status == M.WAITING
    assert run.status == M.WAITING
    assert not h.dispatched
    await asyncio.sleep(0.06)
    assert await h.engine.resume_due(run.run_id)
    run = await h.store.get_run(run.run_id)
    assert run.steps["wait"].status == M.SUCCEEDED
    assert len(h.dispatched) == 1


async def test_approval_step_pauses_run():
    h = Harness()
    await h.setup(wf_doc({
        "gate": {"type": "approval"},
        "deploy": {"topic": "job.t", "depends_on": ["gate"]},
    }))
    run = await h.engine.start_run("wf1", {})
    run = await h.store.get_run(run.run_id)
    assert run.status == M.WAITING_APPROVAL
    assert not h.dispatched
    run = await h.engine.approve_step(run.run_id, "gate", approve=True, approved_by="admin")
    assert len(h.dispatched) == 1
    await h.succeed(h.dispatched[0].job_id, {})
    run = await h.store.get_run(run.run_id)
    assert run.status == M.SUCCEEDED
    tl = await h.store.timeline(run.run_id)
    assert any(e["event"] == "approved" for e in tl)


async def test_approval_rejection_fails_run():
    h = Harness()
    await h.setup(wf_doc({"gate": {"type": "approval"}, "x": {"topic": "job.t", "depends_on": ["gate"]}}))
    run = await h.engine.start_run("wf1", {})
    run = await h.engine.approve_step(run.run_id, "gate", approve=False, approved_by="admin")
    assert run.status == M.FAILED
    assert run.steps["x"].status == M.SKIPPED


async def test_for_each_fanout_with_max_parallel():
    h = Harness()
    await h.setup(wf_doc({
        "fan": {"topic": "job.t", "for_each": "input.items", "max_parallel": 2,
                "input": {"val": "${item}", "idx": "${foreach_index}"}},
    }))
    run = await h.engine.start_run("wf1", {"items": ["a", "b", "c", "d", "e"]})
    assert len(h.dispatched) == 2  # throttled
    ctx0 = await h.mem.get_pointer(h.dispatched[0].context_ptr)
    assert ctx0["item"] == "a" and ctx0["input"] == {"val": "a", "idx": 0}
    # completing one child admits the next
    await h.succeed(h.dispatched[0].job_id, {"r": "a"})
    assert len(h.dispatched) == 3
    for req in list(h.dispatched[1:]):
        await h.succeed(req.job_id, {"r": "x"})
    assert len(h.dispatched) == 5
    for req in list(h.dispatched[3:]):
        await h.succeed(req.job_id, {"r": "y"})
    run = await h.store.get_run(run.run_id)
    assert run.status == M.SUCCEEDED
    agg = run.context["steps"]["fan"]
    assert agg["count"] == 5
    assert agg["children"][0] == {"r": "a"}


async def test_for_each_empty_list_succeeds():
    h = Harness()
    await h.setup(wf_doc({"fan": {"topic": "job.t", "for_each": "input.items"}}))
    run = await h.engine.start_run("wf1", {"items": []})
    run = await h.store.get_run(run.run_id)
    assert run.status == M.SUCCEEDED and not h.dispatched


async def test_for_each_non_list_fails():
    h = Harness()
    await h.setup(wf_doc({"fan": {"topic": "job.t", "for_each": "input.items"}}))
    run = await h.engine.start_run("wf1", {"items": 42})
    run = await h.store.get_run(run.run_id)
    assert run.status == M.FAILED


async def test_for_each_child_failure_fails_parent_and_run():
    h = Harness()
    await h.setup(wf_doc({"fan": {"topic": "job.t", "for_each": "input.items"}}))
    run = await h.engine.start_run("wf1", {"items": [1, 2]})
    await h.succeed(h.dispatched[0].job_id, {})
    await h.fail(h.dispatched[1].job_id, "child exploded")
    run = await h.store.get_run(run.run_id)
    assert run.steps["fan"].status == M.FAILED
    assert run.status == M.FAILED


async def test_retry_with_backoff_then_success():
    h = Harness()
    await h.setup(wf_doc({
        "r": {"topic": "job.t", "retry": {"max_retries": 2, "backoff_sec": 0.02, "multiplier": 1.0}},
    }))
    run = await h.engine.start_run("wf1", {})
    jid1 = h.dispatched[0].job_id
    assert jid1.endswith("@1")
    await h.fail(jid1)
    run = await h.store.get_run(run.run_id)
    assert run.steps["r"].status == M.WAITING
    assert run.status == M.WAITING
    assert not await h.engine.resume_due(run.run_id)  # backoff not elapsed
    await asyncio.sleep(0.03)
    assert await h.engine.resume_due(run.run_id)
    assert len(h.dispatched) == 2 and h.dispatched[1].job_id.endswith("@2")
    await h.succeed(h.dispatched[1].job_id, {"ok": 1})
    run = await h.store.get_run(run.run_id)
    assert run.status == M.SUCCEEDED


async def test_retry_exhaustion_fails():
    h = Harness()
    await h.setup(wf_doc({
        "r": {"topic": "job.t", "retry": {"max_retries": 1, "backoff_sec": 0.01}},
    }))
    run = await h.engine.start_run("wf1", {})
    await h.fail(h.dispatched[0].job_id)
    await asyncio.sleep(0.02)
    await h.engine.resume_due(run.run_id)
    await h.fail(h.dispatched[1].job_id)
    run = await h.store.get_run(run.run_id)
    assert run.steps["r"].status == M.FAILED and run.status == M.FAILED


async def test_stale_attempt_and_duplicate_results_ignored():
    h = Harness()
    await h.setup(wf_doc({
        "r": {"topic": "job.t", "retry": {"max_retries": 3, "backoff_sec": 0.0}},
    }))
    run = await h.engine.start_run("wf1", {})
    jid1 = h.dispatched[0].job_id
    await h.fail(jid1)
    await h.engine.resume_due(run.run_id)
    jid2 = h.dispatched[1].job_id
    # stale result for attempt 1 arrives late: ignored
    await h.succeed(jid1, {"stale": True})
    run2 = await h.store.get_run(run.run_id)
    assert run2.steps["r"].status == M.RUNNING
    await h.succeed(jid2, {"fresh": True})
    await h.succeed(jid2, {"dup": True})  # duplicate redelivery: no-op
    run3 = await h.store.get_run(run.run_id)
    assert run3.context["steps"]["r"] == {"fresh": True}


async def test_on_error_continue():
    h = Harness()
    await h.setup(wf_doc({
        "flaky": {"topic": "job.t", "on_error": "continue"},
        "next": {"topic": "job.t", "depends_on": ["flaky"]},
    }))
    run = await h.engine.start_run("wf1", {})
    await h.fail(h.dispatched[0].job_id)
    run = await h.store.get_run(run.run_id)
    assert run.steps["flaky"].status == M.FAILED
    # continue-on-error: the dependent still runs and the run can succeed
    assert len(h.dispatched) == 2
    await h.succeed(h.dispatched[1].job_id, {"ok": 1})
    run = await h.store.get_run(run.run_id)
    assert run.steps["next"].status == M.SUCCEEDED
    assert run.status == M.SUCCEEDED


async def test_output_path_and_schema_validation():
    h = Harness()
    await h.schemas.put("out1", {"type": "object", "required": ["score"]})
    await h.setup(wf_doc({
        "s": {"topic": "job.t", "output_schema_id": "out1", "output_path": "results.final"},
    }))
    run = await h.engine.start_run("wf1", {})
    await h.succeed(h.dispatched[0].job_id, {"score": 9})
    run = await h.store.get_run(run.run_id)
    assert run.context["results"]["final"] == {"score": 9}
    # invalid output fails the step
    run2 = await h.engine.start_run("wf1", {})
    await h.succeed(h.dispatched[1].job_id, {"wrong": 1})
    run2 = await h.store.get_run(run2.run_id)
    assert run2.status == M.FAILED


async def test_input_schema_validation_blocks_run():
    h = Harness()
    await h.schemas.put("in1", {"type": "object", "required": ["x"]})
    await h.setup(wf_doc({"s": {"topic": "job.t"}}, input_schema_id="in1"))
    from cordum_tpu.workflow.engine import WorkflowError

    with pytest.raises(WorkflowError):
        await h.engine.start_run("wf1", {"y": 1})


async def test_run_idempotency_key():
    h = Harness()
    await h.setup(wf_doc({"s": {"topic": "job.t"}}))
    r1 = await h.engine.start_run("wf1", {}, idempotency_key="k1")
    r2 = await h.engine.start_run("wf1", {}, idempotency_key="k1")
    assert r1.run_id == r2.run_id
    assert len(h.dispatched) == 1


async def test_cancel_run_broadcasts_jobcancel():
    h = Harness()
    cancels = []

    async def tap(subject, pkt):
        cancels.append(pkt.job_cancel.job_id)

    await h.bus.subscribe(subj.CANCEL, tap)
    await h.setup(wf_doc({"s": {"topic": "job.t"}, "t": {"topic": "job.t"}}))
    run = await h.engine.start_run("wf1", {})
    run = await h.engine.cancel_run(run.run_id, reason="user")
    assert run.status == M.CANCELLED
    assert len(cancels) == 2


async def test_rerun_from_resets_dependent_closure():
    h = Harness()
    await h.setup(wf_doc({
        "a": {"topic": "job.t"},
        "b": {"topic": "job.t", "depends_on": ["a"]},
        "c": {"topic": "job.t", "depends_on": ["b"]},
        "other": {"topic": "job.t"},
    }))
    run = await h.engine.start_run("wf1", {})
    # complete steps as they dispatch until the run succeeds
    applied = 0
    while (await h.store.get_run(run.run_id)).status != M.SUCCEEDED:
        for req in h.dispatched[applied:]:
            applied += 1
            await h.succeed(req.job_id, {"from": req.job_id.split(":")[1].split("@")[0]})
    n_before = len(h.dispatched)
    rerun = await h.engine.rerun_from(run.run_id, "b")
    # only b redispATCHED (a and other preserved), c reset pending on b
    new = h.dispatched[n_before:]
    assert len(new) == 1 and new[0].job_id.startswith(rerun.run_id) and ":b@" in new[0].job_id
    assert rerun.steps["a"].status == M.SUCCEEDED
    assert rerun.steps["other"].status == M.SUCCEEDED
    await h.succeed(new[0].job_id, {})
    new2 = h.dispatched[n_before + 1:]
    assert len(new2) == 1 and ":c@" in new2[0].job_id


async def test_dry_run_labels_jobs():
    h = Harness()
    await h.setup(wf_doc({"s": {"topic": "job.t"}}))
    await h.engine.start_run("wf1", {}, dry_run=True)
    assert h.dispatched[0].labels.get("cordum.dry_run") == "true"


async def test_step_meta_flows_to_job_metadata():
    h = Harness()
    await h.setup(wf_doc({
        "s": {"topic": "job.tpu.infer", "meta": {"capability": "tpu", "requires": ["tpu", "chips:4"]},
              "route_labels": {"preferred_pool": "tpu"}},
    }))
    await h.engine.start_run("wf1", {})
    req = h.dispatched[0]
    assert req.metadata.capability == "tpu"
    assert req.metadata.requires == ["tpu", "chips:4"]
    assert req.labels["preferred_pool"] == "tpu"


async def test_workflow_validate():
    wf = Workflow.from_dict(wf_doc({"a": {"topic": "t", "depends_on": ["zzz"]}}))
    assert any("unknown dependency" in e for e in wf.validate())
    cyc = Workflow.from_dict(wf_doc({
        "a": {"topic": "t", "depends_on": ["b"]},
        "b": {"topic": "t", "depends_on": ["a"]},
    }))
    assert any("cycle" in e for e in cyc.validate())
    nob = Workflow.from_dict(wf_doc({"a": {"type": "worker"}}))
    assert any("needs a topic" in e for e in nob.validate())


# ---------------------------------------------------------------- store

async def test_workflow_store_roundtrip(kv):
    store = WorkflowStore(kv)
    wf = Workflow.from_dict(wf_doc({"s": {"topic": "job.t"}}, org_id="acme"))
    await store.put_workflow(wf)
    back = await store.get_workflow("wf1")
    assert back.steps["s"].topic == "job.t"
    assert "wf1" in await store.list_workflows()
    assert await store.delete_workflow("wf1")


async def test_run_status_indexes(kv):
    from cordum_tpu.workflow.models import WorkflowRun

    store = WorkflowStore(kv)
    run = WorkflowRun(run_id="r1", workflow_id="wf1", org_id="o", status=M.RUNNING, created_at_us=1)
    await store.put_run(run)
    assert "r1" in await store.list_run_ids_by_status(M.RUNNING)
    assert await store.count_active_runs("o") == 1
    run.status = M.SUCCEEDED
    await store.put_run(run)
    assert "r1" not in await store.list_run_ids_by_status(M.RUNNING)
    assert "r1" in await store.list_run_ids_by_status(M.SUCCEEDED)
    assert await store.count_active_runs("o") == 0


# ------------------------------------------------- agentic serving (DAG ⇄ pool)

async def test_slo_class_becomes_job_priority():
    from cordum_tpu.protocol.types import LABEL_SLO_CLASS

    h = Harness()
    await h.setup(wf_doc({"a": {"topic": "job.t", "input": {"op": "echo"}}},
                         slo_class="interactive"))
    run = await h.engine.start_run("wf1", {})
    # resolved once, pinned as a run label, read back on every dispatch
    assert run.labels[LABEL_SLO_CLASS] == "INTERACTIVE"
    assert h.dispatched[0].priority == "INTERACTIVE"


async def test_slo_run_label_overrides_workflow_default():
    from cordum_tpu.protocol.types import LABEL_SLO_CLASS

    h = Harness()
    await h.setup(wf_doc({"a": {"topic": "job.t"}}, slo_class="BATCH"))
    run = await h.engine.start_run("wf1", {}, labels={LABEL_SLO_CLASS: "CRITICAL"})
    assert run.labels[LABEL_SLO_CLASS] == "CRITICAL"
    assert h.dispatched[0].priority == "CRITICAL"


async def test_unknown_slo_class_rejected_and_defaulted():
    # validate() rejects it at workflow-create time…
    wf = Workflow.from_dict(wf_doc({"a": {"topic": "t"}}, slo_class="GOLD"))
    assert any("slo_class" in e for e in wf.validate())
    # …and a bogus value smuggled past validation degrades to BATCH priority
    h = Harness()
    wf2 = Workflow.from_dict(wf_doc({"a": {"topic": "job.t"}}, slo_class="GOLD"))
    await h.store.put_workflow(wf2)

    async def capture(subject, pkt):
        if pkt.job_request:
            h.dispatched.append(pkt.job_request)

    await h.bus.subscribe(subj.SUBMIT, capture)
    await h.engine.start_run("wf1", {})
    assert h.dispatched[0].priority == "BATCH"


async def test_serving_step_gets_session_stamped():
    from cordum_tpu.protocol.types import LABEL_SESSION_KEY

    h = Harness()
    await h.setup(wf_doc({
        "gen": {"topic": "job.tpu.generate",
                "input": {"op": "llm.generate", "tokens": [1, 2], "max_new_tokens": 4}},
        "other": {"topic": "job.t", "input": {"op": "echo"}},
    }))
    run = await h.engine.start_run("wf1", {})
    by_step = {r.job_id.split(":")[1].split("@")[0]: r for r in h.dispatched}
    # payload: the serving op defaults session_id to the per-run key…
    gen_payload = await h.mem.get_pointer(by_step["gen"].context_ptr)
    assert gen_payload["session_id"] == f"wf:{run.run_id}"
    # …and the routing label matches, so session affinity steers the job
    assert by_step["gen"].labels[LABEL_SESSION_KEY] == f"wf:{run.run_id}"
    # non-serving steps get neither
    other_payload = await h.mem.get_pointer(by_step["other"].context_ptr)
    assert "session_id" not in other_payload
    assert LABEL_SESSION_KEY not in by_step["other"].labels


async def test_session_key_label_carries_across_runs():
    """Two runs started with the same cordum.session_key label land on ONE
    serving session — the cross-turn agent-loop continuity contract."""
    from cordum_tpu.protocol.types import LABEL_SESSION_KEY

    h = Harness()
    await h.setup(wf_doc({
        "gen": {"topic": "job.tpu.generate", "input": {"op": "llm.generate"}}}))
    for _ in range(2):
        await h.engine.start_run("wf1", {}, labels={LABEL_SESSION_KEY: "sess-9"})
    assert len(h.dispatched) == 2
    for req in h.dispatched:
        assert req.labels[LABEL_SESSION_KEY] == "sess-9"
        payload = await h.mem.get_pointer(req.context_ptr)
        assert payload["session_id"] == "sess-9"


async def test_explicit_session_id_wins_over_run_key():
    h = Harness()
    await h.setup(wf_doc({
        "gen": {"topic": "job.tpu.generate",
                "input": {"op": "llm.generate", "session_id": "pinned"}}}))
    await h.engine.start_run("wf1", {})
    payload = await h.mem.get_pointer(h.dispatched[0].context_ptr)
    assert payload["session_id"] == "pinned"


class _InlineEmbedder:
    """Sync embedder: deterministic unit-norm hash vectors (test-local)."""

    dim = 8

    def embed(self, texts):
        import numpy as np

        out = np.zeros((len(texts), self.dim), dtype=np.float32)
        for i, t in enumerate(texts):
            out[i, hash(t) % self.dim] = 1.0
        return out


def _context_harness():
    from cordum_tpu.context.service import ContextService

    h = Harness()
    h.engine.context_svc = ContextService(h.kv, embedder=_InlineEmbedder())
    return h


async def _drain_until_terminal(h, run_id, rounds=20):
    for _ in range(rounds):
        await h.engine.drain_context_steps()
        run = await h.store.get_run(run_id)
        if run.status in M.RUN_TERMINAL:
            return run
        await asyncio.sleep(0.01)
    return await h.store.get_run(run_id)


async def test_context_steps_execute_in_engine():
    """context.update / context.window run through the ContextService and
    never reach the scheduler (no SUBMIT for them)."""
    h = _context_harness()
    await h.setup(wf_doc({
        "up": {"topic": "job.tpu.context",
               "input": {"op": "context.update", "user_payload": "hello",
                         "model_response": "world",
                         "chunks": [{"file_path": "notes", "content": "alpha beta"}]}},
        "win": {"topic": "job.tpu.context", "depends_on": ["up"],
                "input": {"op": "context.window", "mode": "RAG", "query": "alpha"}},
    }))
    run = await h.engine.start_run("wf1", {})
    run = await _drain_until_terminal(h, run.run_id)
    assert run.status == M.SUCCEEDED, (run.status, run.error)
    assert h.dispatched == []  # the scheduler never saw these jobs
    up = run.context["steps"]["up"]
    assert up["updated"] and up["embedded"] == 1
    win = run.context["steps"]["win"]
    assert win["message_count"] >= 1
    # the memory defaults to the run session key → agent loop reads its own writes
    assert up["memory_id"] == f"wf:{run.run_id}" == win["memory_id"]


async def test_context_step_without_service_fails_step():
    h = Harness()  # no context_svc wired
    await h.setup(wf_doc({
        "up": {"topic": "job.tpu.context", "input": {"op": "context.update"}}}))
    run = await h.engine.start_run("wf1", {})
    run = await _drain_until_terminal(h, run.run_id)
    assert run.status == M.FAILED
    assert "context service" in (run.steps["up"].error or "")


async def test_run_is_one_trace_with_root_span():
    h = Harness()
    spans = []

    async def tap(subject, pkt):
        if pkt.span is not None:
            spans.append(pkt.span)

    await h.bus.subscribe(subj.TRACE_SPAN, tap)
    await h.setup(wf_doc({
        "a": {"topic": "job.t"},
        "b": {"topic": "job.t", "depends_on": ["a"]},
    }))
    run = await h.engine.start_run("wf1", {})
    assert run.trace_id and run.root_span_id
    await h.succeed(h.dispatched[0].job_id, {})
    await h.succeed(h.dispatched[1].job_id, {})
    fin = await h.store.get_run(run.run_id)
    assert fin.status == M.SUCCEEDED
    # every span of the run shares ONE trace id
    assert spans and {s.trace_id for s in spans} == {run.trace_id}
    dispatch = [s for s in spans if s.name == "step-dispatch"]
    assert len(dispatch) == 2
    # …and parents under the run root span, which is emitted at run end
    assert {s.parent_span_id for s in dispatch} == {run.root_span_id}
    roots = [s for s in spans if s.name == "workflow-run"]
    assert len(roots) == 1 and roots[0].span_id == run.root_span_id
    # root span brackets the whole run (starts at created_at, not at finish)
    assert roots[0].start_us <= dispatch[0].start_us


async def test_workflow_metrics_families_increment():
    h = Harness()
    await h.setup(wf_doc({"a": {"topic": "job.t"}}))
    run = await h.engine.start_run("wf1", {})
    await h.succeed(h.dispatched[0].job_id, {})
    text = h.engine.metrics.render()
    assert 'cordum_workflow_runs_total{status="STARTED"}' in text
    assert 'cordum_workflow_runs_total{status="SUCCEEDED"}' in text
    assert 'cordum_workflow_steps_total{topic="job.t"}' in text
    assert "cordum_workflow_step_seconds" in text


def test_floor_checker_gates_agents_keys():
    import sys
    from pathlib import Path

    repo = Path(__file__).resolve().parents[1]
    sys.path.insert(0, str(repo / "tools"))
    try:
        import check_bench_floor as mod
    finally:
        sys.path.pop(0)
    floors = json.loads((repo / "bench_floor.json").read_text())
    base = {"agents_workflow_steps_per_sec": 40.0, "agents_step_p99_ms": 20.0,
            "agents_affinity_hit_rate": 1.0, "agents_reprefills": 0.0,
            "agents_context_embeds_per_sec": 50.0}
    # healthy values: no agents-key violations (other keys flag missing)
    assert not any("agents" in v for v in mod.check(dict(base), floors))
    for key, bad in [("agents_workflow_steps_per_sec", 1.0),
                     ("agents_step_p99_ms", 5000.0),
                     ("agents_affinity_hit_rate", 0.5),
                     ("agents_reprefills", 3.0),
                     ("agents_context_embeds_per_sec", 0.0)]:
        doc = dict(base)
        doc[key] = bad
        assert any(key in v for v in mod.check(doc, floors)), key
    # a missing agents key is itself a violation (the gate cannot be skipped)
    doc = dict(base)
    doc.pop("agents_reprefills")
    assert any("agents_reprefills" in v for v in mod.check(doc, floors))
