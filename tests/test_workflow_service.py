"""Full-stack workflow integration: workflow engine service + scheduler +
worker over the loopback bus — the reference's platform_smoke.sh flow
(workflow create → run → approve → succeeded) plus fan-out."""
import asyncio

import pytest

from cordum_tpu.controlplane.safetykernel.kernel import SafetyKernel
from cordum_tpu.controlplane.scheduler.engine import Engine as Scheduler
from cordum_tpu.controlplane.scheduler.safety_client import SafetyClient
from cordum_tpu.controlplane.scheduler.strategy import LeastLoadedStrategy
from cordum_tpu.controlplane.workflowengine.service import WorkflowEngineService
from cordum_tpu.infra.bus import LoopbackBus
from cordum_tpu.infra.config import parse_pool_config
from cordum_tpu.infra.jobstore import JobStore
from cordum_tpu.infra.kv import MemoryKV
from cordum_tpu.infra.memstore import MemoryStore
from cordum_tpu.infra.registry import WorkerRegistry
from cordum_tpu.infra.schemareg import SchemaRegistry
from cordum_tpu.workflow import models as M
from cordum_tpu.workflow.engine import Engine as WorkflowEngine
from cordum_tpu.workflow.models import Workflow
from cordum_tpu.workflow.store import WorkflowStore
from cordum_tpu.worker.runtime import JobContext, Worker


async def settle(bus, rounds=8):
    for _ in range(rounds):
        await bus.drain()
        await asyncio.sleep(0.02)


class Stack:
    def __init__(self):
        self.kv = MemoryKV()
        self.bus = LoopbackBus()
        self.job_store = JobStore(self.kv)
        self.mem = MemoryStore(self.kv)
        self.wf_store = WorkflowStore(self.kv)
        self.schemas = SchemaRegistry(self.kv)
        kernel = SafetyKernel(policy_doc={})
        self.registry = WorkerRegistry()
        pc = parse_pool_config({"topics": {"job.work": "p"}, "pools": {"p": {}}})
        self.scheduler = Scheduler(
            bus=self.bus, job_store=self.job_store, safety=SafetyClient(kernel.check),
            strategy=LeastLoadedStrategy(self.registry, pc), registry=self.registry,
        )
        self.wf_engine = WorkflowEngine(
            store=self.wf_store, bus=self.bus, mem=self.mem, schemas=self.schemas
        )
        self.wf_service = WorkflowEngineService(
            engine=self.wf_engine, bus=self.bus, job_store=self.job_store,
            reconcile_interval_s=0.05,
        )
        self.worker = Worker(bus=self.bus, store=self.mem, worker_id="w1", pool="p",
                             topics=["job.work"], heartbeat_interval_s=999)

    async def start(self, handler):
        self.worker.register("job.work", handler)
        await self.scheduler.start()
        await self.wf_service.start()
        await self.worker.start()
        await settle(self.bus)

    async def stop(self):
        await self.worker.stop()
        await self.wf_service.stop()
        await self.scheduler.stop()
        await self.bus.close()

    async def wait_run(self, run_id, timeout_s=10.0):
        for _ in range(int(timeout_s / 0.05)):
            await settle(self.bus, rounds=2)
            run = await self.wf_store.get_run(run_id)
            if run and run.status in M.RUN_TERMINAL:
                return run
            await asyncio.sleep(0.02)
        return await self.wf_store.get_run(run_id)


async def test_full_stack_workflow_with_fanout():
    s = Stack()

    async def handler(ctx: JobContext):
        p = ctx.payload or {}
        if isinstance(p, dict) and "item" in p:
            return {"squared": p["item"] * p["item"]}
        return {"n": (p or {}).get("n", 0) if isinstance(p, dict) else 0, "list": [1, 2, 3]}

    await s.start(handler)
    wf = Workflow.from_dict({
        "id": "smoke", "name": "smoke",
        "steps": {
            "seed": {"topic": "job.work", "input": {"n": "${input.n}"}},
            "fan": {"topic": "job.work", "depends_on": ["seed"],
                    "for_each": "steps.seed.list", "max_parallel": 2},
            "done": {"type": "notify", "depends_on": ["fan"],
                     "notify_message": "all ${length(steps.fan.children)} done"},
        },
    })
    await s.wf_store.put_workflow(wf)
    run = await s.wf_engine.start_run("smoke", {"n": 7})
    run = await s.wait_run(run.run_id)
    assert run.status == M.SUCCEEDED, (run.status, run.error,
                                       {k: v.status for k, v in run.steps.items()})
    children = run.context["steps"]["fan"]["children"]
    assert children == [{"squared": 1}, {"squared": 4}, {"squared": 9}]
    # scheduler tracked every job too
    tl = await s.wf_store.timeline(run.run_id)
    assert any(e["event"] == "notified" and "3" in e["detail"] for e in tl)
    await s.stop()


async def test_full_stack_approval_smoke():
    """platform_smoke.sh equivalent: approval-only workflow, zero workers."""
    s = Stack()

    async def handler(ctx):  # never called
        return {}

    await s.start(handler)
    wf = Workflow.from_dict({
        "id": "appr", "name": "appr",
        "steps": {"gate": {"type": "approval"},
                  "note": {"type": "notify", "depends_on": ["gate"], "notify_message": "approved!"}},
    })
    await s.wf_store.put_workflow(wf)
    run = await s.wf_engine.start_run("appr", {})
    assert run.status == M.WAITING_APPROVAL
    run = await s.wf_engine.approve_step(run.run_id, "gate", approve=True, approved_by="admin")
    run = await s.wait_run(run.run_id)
    assert run.status == M.SUCCEEDED
    await s.stop()


async def test_full_stack_worker_failure_retry_via_reconciler():
    s = Stack()
    calls = {"n": 0}

    async def handler(ctx: JobContext):
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("first try fails")
        return {"ok": True}

    await s.start(handler)
    wf = Workflow.from_dict({
        "id": "retry", "name": "retry",
        "steps": {"r": {"topic": "job.work",
                        "retry": {"max_retries": 2, "backoff_sec": 0.05, "multiplier": 1.0}}},
    })
    await s.wf_store.put_workflow(wf)
    run = await s.wf_engine.start_run("retry", {})
    run = await s.wait_run(run.run_id, timeout_s=15)
    assert run.status == M.SUCCEEDED, (run.status, run.error)
    assert calls["n"] == 2
    await s.stop()


async def test_full_stack_reconciler_replays_missed_result():
    """Kill the wf service before the result lands; the reconciler must
    replay the terminal job state from the JobStore (crash recovery)."""
    s = Stack()
    gate = asyncio.Event()

    async def handler(ctx):
        await gate.wait()
        return {"late": True}

    await s.start(handler)
    wf = Workflow.from_dict({"id": "cr", "name": "cr", "steps": {"s": {"topic": "job.work"}}})
    await s.wf_store.put_workflow(wf)
    run = await s.wf_engine.start_run("cr", {})
    # plain sleeps (not drain): the in-flight worker task is parked on `gate`
    # and draining would deadlock on it
    await asyncio.sleep(0.1)
    # detach the wf service from the bus AND pause its reconcile loop
    # (simulated crash), then finish the job
    for sub in s.wf_service._subs:
        sub.unsubscribe()
    s.wf_service._task.cancel()
    gate.set()
    await settle(s.bus, rounds=10)
    # scheduler recorded SUCCEEDED in job store; run still RUNNING
    mid = await s.wf_store.get_run(run.run_id)
    assert mid.status == M.RUNNING
    # reconciler replays from job store
    n = await s.wf_service.reconcile_once()
    assert n >= 1
    fin = await s.wf_store.get_run(run.run_id)
    assert fin.status == M.SUCCEEDED
    assert fin.context["steps"]["s"] == {"late": True}
    await s.stop()


async def test_run_lock_nak_backoff_grows_with_redeliveries():
    """Contended run lock → RetryAfter whose delay grows exponentially with
    the redelivery count (jittered ±25 %), capped at MAX_NAK_DELAY_S."""
    from cordum_tpu.controlplane.workflowengine.service import RUN_LOCK_NAK_BASE_S
    from cordum_tpu.infra.bus import MAX_NAK_DELAY_S, RetryAfter
    from cordum_tpu.protocol.types import JobResult

    s = Stack()
    # another replica holds the run lock
    assert await s.wf_store.acquire_run_lock("run-x", "other-replica")
    res = JobResult(job_id="run-x:step@1", status="SUCCEEDED")
    delays = []
    for redeliveries in range(12):
        with pytest.raises(RetryAfter) as ei:
            await s.wf_service.handle_job_result(res, redeliveries=redeliveries)
        delays.append(ei.value.delay_s)
        base = min(MAX_NAK_DELAY_S, RUN_LOCK_NAK_BASE_S * (2 ** redeliveries))
        assert base * 0.75 <= delays[-1] <= base * 1.25
    assert delays[-1] <= MAX_NAK_DELAY_S * 1.25  # capped, jitter rides on top
    # non-workflow job ids pass straight through (no lock, no raise)
    await s.wf_service.handle_job_result(JobResult(job_id="plain-id", status="SUCCEEDED"))
    await s.bus.close()


async def test_reconcile_skips_runs_locked_by_other_replica():
    from cordum_tpu.workflow.models import WorkflowRun

    s = Stack()
    for rid in ("r-held", "r-free"):
        await s.wf_store.put_run(WorkflowRun(
            run_id=rid, workflow_id="nope", org_id="o",
            status=M.RUNNING, created_at_us=1))
    assert await s.wf_store.acquire_run_lock("r-held", "other-replica")
    touched = []
    orig = s.wf_engine.resume_due

    async def spy(run_id):
        touched.append(run_id)
        return await orig(run_id)

    s.wf_engine.resume_due = spy
    await s.wf_service.reconcile_once()
    # the held run is skipped off the lock-prefix scan; the free one is visited
    assert touched == ["r-free"]
    # live-run gauge reflects the batched status scan
    assert "cordum_workflow_active_runs 2.0" in s.wf_engine.metrics.render()
    await s.bus.close()


async def test_replay_equivalent_to_live_result_path():
    """Satellite: the reconciler's JobStore replay must produce the same run
    state as the live bus path — same step output, status, and a faithful
    execution_ms carried from the job meta audit trail."""
    s = Stack()
    gate = asyncio.Event()
    gate.set()  # live path runs unblocked
    done = asyncio.Event()

    async def handler(ctx):
        await gate.wait()
        done.set()
        return {"answer": 42}

    await s.start(handler)
    wf = Workflow.from_dict(
        {"id": "eq", "name": "eq", "steps": {"s": {"topic": "job.work"}}})
    await s.wf_store.put_workflow(wf)

    # live path
    live = await s.wf_engine.start_run("eq", {})
    live = await s.wait_run(live.run_id)
    assert live.status == M.SUCCEEDED

    # replayed path: park the worker, detach the service (simulated crash),
    # then let the result land with nobody listening
    gate.clear()
    done.clear()
    replay = await s.wf_engine.start_run("eq", {})
    await asyncio.sleep(0.05)  # plain sleep: a drain would park on `gate`
    for sub in s.wf_service._subs:
        sub.unsubscribe()
    s.wf_service._task.cancel()
    gate.set()
    await done.wait()
    await settle(s.bus, rounds=10)
    assert (await s.wf_store.get_run(replay.run_id)).status == M.RUNNING
    job_id = f"{replay.run_id}:s@1"
    meta = await s.job_store.get_meta(job_id)
    assert meta.get("state") == "SUCCEEDED" and meta.get("execution_ms")
    assert await s.wf_service.reconcile_once() >= 1
    replay = await s.wf_store.get_run(replay.run_id)

    # equivalence: identical step output, status, and worker attribution
    assert replay.status == live.status == M.SUCCEEDED
    assert replay.context["steps"]["s"] == live.context["steps"]["s"] == {"answer": 42}
    assert replay.steps["s"].status == live.steps["s"].status
    await s.stop()


async def test_rerun_from_full_stack():
    """rerun_from re-executes the failed closure through the real
    scheduler+worker and reuses upstream outputs without re-dispatching."""
    s = Stack()
    flaky = {"ok": False}

    async def handler(ctx):
        p = ctx.payload or {}
        if p.get("which") == "b" and not flaky["ok"]:
            raise RuntimeError("b broken")
        return {"which": p.get("which"), "ran": True}

    await s.start(handler)
    wf = Workflow.from_dict({
        "id": "rr", "name": "rr",
        "steps": {"a": {"topic": "job.work", "input": {"which": "a"}},
                  "b": {"topic": "job.work", "depends_on": ["a"],
                        "input": {"which": "b"}}},
    })
    await s.wf_store.put_workflow(wf)
    run = await s.wf_engine.start_run("rr", {})
    run = await s.wait_run(run.run_id)
    assert run.status == M.FAILED

    flaky["ok"] = True
    rerun = await s.wf_engine.rerun_from(run.run_id, "b")
    # a rerun is its own trace (fresh waterfall), linked via the timeline
    assert rerun.trace_id and rerun.trace_id != run.trace_id
    rerun = await s.wait_run(rerun.run_id)
    assert rerun.status == M.SUCCEEDED, (rerun.status, rerun.error)
    # upstream output carried over; only b re-dispatched in the rerun
    assert rerun.context["steps"]["a"] == {"which": "a", "ran": True}
    assert rerun.steps["a"].job_id == run.steps["a"].job_id  # not re-run
    assert rerun.steps["b"].job_id.startswith(rerun.run_id)
    tl = await s.wf_store.timeline(rerun.run_id)
    assert any(e["event"] == "rerun_from" and e["detail"] == run.run_id for e in tl)
    await s.stop()


async def test_approval_rejection_fails_run_full_stack():
    s = Stack()

    async def handler(ctx):  # the deploy step must never run
        raise AssertionError("dispatched past a rejected gate")

    await s.start(handler)
    wf = Workflow.from_dict({
        "id": "rej", "name": "rej",
        "steps": {"gate": {"type": "approval"},
                  "deploy": {"topic": "job.work", "depends_on": ["gate"]}},
    })
    await s.wf_store.put_workflow(wf)
    run = await s.wf_engine.start_run("rej", {})
    assert run.status == M.WAITING_APPROVAL
    run = await s.wf_engine.approve_step(
        run.run_id, "gate", approve=False, approved_by="sec")
    run = await s.wait_run(run.run_id)
    assert run.status == M.FAILED
    assert run.steps["deploy"].status in (M.PENDING, M.SKIPPED, M.CANCELLED)
    await s.stop()


async def test_cancel_mid_fanout_leaves_no_orphan_jobs():
    """Cancelling a run while fan-out children are in flight must cancel
    every dispatched job — nothing keeps running or pending in the
    scheduler/worker after the run is CANCELLED."""
    from cordum_tpu.protocol.types import TERMINAL_STATES

    s = Stack()
    gate = asyncio.Event()
    started = asyncio.Event()

    async def handler(ctx):
        p = ctx.payload or {}
        if isinstance(p, dict) and "item" in p:
            started.set()
            await gate.wait()  # children park here until released
            return {"done": p["item"]}
        return {"list": [1, 2, 3]}

    await s.start(handler)
    wf = Workflow.from_dict({
        "id": "cx", "name": "cx",
        "steps": {"seed": {"topic": "job.work"},
                  "fan": {"topic": "job.work", "depends_on": ["seed"],
                          "for_each": "steps.seed.list", "max_parallel": 2}},
    })
    await s.wf_store.put_workflow(wf)
    run = await s.wf_engine.start_run("cx", {})
    # plain sleeps: parked worker tasks would deadlock a drain
    for _ in range(200):
        if started.is_set():
            break
        await asyncio.sleep(0.01)
    assert started.is_set(), "fan-out children never started"

    run = await s.wf_engine.cancel_run(run.run_id, reason="operator abort")
    assert run.status == M.CANCELLED
    gate.set()
    await settle(s.bus, rounds=10)

    # every job the run ever dispatched is terminal in the job store
    run = await s.wf_store.get_run(run.run_id)
    job_ids = [t.job_id
               for sr in run.steps.values()
               for t in [sr, *sr.children.values()] if t.job_id]
    assert job_ids, "expected dispatched jobs"
    terminal = {st.value for st in TERMINAL_STATES}
    for jid in job_ids:
        meta = await s.job_store.get_meta(jid)
        assert meta.get("state") in terminal, (jid, meta.get("state"))
    # and no step (parent or child) is left non-terminal
    for sr in run.steps.values():
        for t in [sr, *sr.children.values()]:
            assert t.status in M.STEP_TERMINAL, (t.step_id, t.status)
    await s.stop()
