"""Full-stack workflow integration: workflow engine service + scheduler +
worker over the loopback bus — the reference's platform_smoke.sh flow
(workflow create → run → approve → succeeded) plus fan-out."""
import asyncio

import pytest

from cordum_tpu.controlplane.safetykernel.kernel import SafetyKernel
from cordum_tpu.controlplane.scheduler.engine import Engine as Scheduler
from cordum_tpu.controlplane.scheduler.safety_client import SafetyClient
from cordum_tpu.controlplane.scheduler.strategy import LeastLoadedStrategy
from cordum_tpu.controlplane.workflowengine.service import WorkflowEngineService
from cordum_tpu.infra.bus import LoopbackBus
from cordum_tpu.infra.config import parse_pool_config
from cordum_tpu.infra.jobstore import JobStore
from cordum_tpu.infra.kv import MemoryKV
from cordum_tpu.infra.memstore import MemoryStore
from cordum_tpu.infra.registry import WorkerRegistry
from cordum_tpu.infra.schemareg import SchemaRegistry
from cordum_tpu.workflow import models as M
from cordum_tpu.workflow.engine import Engine as WorkflowEngine
from cordum_tpu.workflow.models import Workflow
from cordum_tpu.workflow.store import WorkflowStore
from cordum_tpu.worker.runtime import JobContext, Worker


async def settle(bus, rounds=8):
    for _ in range(rounds):
        await bus.drain()
        await asyncio.sleep(0.02)


class Stack:
    def __init__(self):
        self.kv = MemoryKV()
        self.bus = LoopbackBus()
        self.job_store = JobStore(self.kv)
        self.mem = MemoryStore(self.kv)
        self.wf_store = WorkflowStore(self.kv)
        self.schemas = SchemaRegistry(self.kv)
        kernel = SafetyKernel(policy_doc={})
        self.registry = WorkerRegistry()
        pc = parse_pool_config({"topics": {"job.work": "p"}, "pools": {"p": {}}})
        self.scheduler = Scheduler(
            bus=self.bus, job_store=self.job_store, safety=SafetyClient(kernel.check),
            strategy=LeastLoadedStrategy(self.registry, pc), registry=self.registry,
        )
        self.wf_engine = WorkflowEngine(
            store=self.wf_store, bus=self.bus, mem=self.mem, schemas=self.schemas
        )
        self.wf_service = WorkflowEngineService(
            engine=self.wf_engine, bus=self.bus, job_store=self.job_store,
            reconcile_interval_s=0.05,
        )
        self.worker = Worker(bus=self.bus, store=self.mem, worker_id="w1", pool="p",
                             topics=["job.work"], heartbeat_interval_s=999)

    async def start(self, handler):
        self.worker.register("job.work", handler)
        await self.scheduler.start()
        await self.wf_service.start()
        await self.worker.start()
        await settle(self.bus)

    async def stop(self):
        await self.worker.stop()
        await self.wf_service.stop()
        await self.scheduler.stop()
        await self.bus.close()

    async def wait_run(self, run_id, timeout_s=10.0):
        for _ in range(int(timeout_s / 0.05)):
            await settle(self.bus, rounds=2)
            run = await self.wf_store.get_run(run_id)
            if run and run.status in M.RUN_TERMINAL:
                return run
            await asyncio.sleep(0.02)
        return await self.wf_store.get_run(run_id)


async def test_full_stack_workflow_with_fanout():
    s = Stack()

    async def handler(ctx: JobContext):
        p = ctx.payload or {}
        if isinstance(p, dict) and "item" in p:
            return {"squared": p["item"] * p["item"]}
        return {"n": (p or {}).get("n", 0) if isinstance(p, dict) else 0, "list": [1, 2, 3]}

    await s.start(handler)
    wf = Workflow.from_dict({
        "id": "smoke", "name": "smoke",
        "steps": {
            "seed": {"topic": "job.work", "input": {"n": "${input.n}"}},
            "fan": {"topic": "job.work", "depends_on": ["seed"],
                    "for_each": "steps.seed.list", "max_parallel": 2},
            "done": {"type": "notify", "depends_on": ["fan"],
                     "notify_message": "all ${length(steps.fan.children)} done"},
        },
    })
    await s.wf_store.put_workflow(wf)
    run = await s.wf_engine.start_run("smoke", {"n": 7})
    run = await s.wait_run(run.run_id)
    assert run.status == M.SUCCEEDED, (run.status, run.error,
                                       {k: v.status for k, v in run.steps.items()})
    children = run.context["steps"]["fan"]["children"]
    assert children == [{"squared": 1}, {"squared": 4}, {"squared": 9}]
    # scheduler tracked every job too
    tl = await s.wf_store.timeline(run.run_id)
    assert any(e["event"] == "notified" and "3" in e["detail"] for e in tl)
    await s.stop()


async def test_full_stack_approval_smoke():
    """platform_smoke.sh equivalent: approval-only workflow, zero workers."""
    s = Stack()

    async def handler(ctx):  # never called
        return {}

    await s.start(handler)
    wf = Workflow.from_dict({
        "id": "appr", "name": "appr",
        "steps": {"gate": {"type": "approval"},
                  "note": {"type": "notify", "depends_on": ["gate"], "notify_message": "approved!"}},
    })
    await s.wf_store.put_workflow(wf)
    run = await s.wf_engine.start_run("appr", {})
    assert run.status == M.WAITING_APPROVAL
    run = await s.wf_engine.approve_step(run.run_id, "gate", approve=True, approved_by="admin")
    run = await s.wait_run(run.run_id)
    assert run.status == M.SUCCEEDED
    await s.stop()


async def test_full_stack_worker_failure_retry_via_reconciler():
    s = Stack()
    calls = {"n": 0}

    async def handler(ctx: JobContext):
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("first try fails")
        return {"ok": True}

    await s.start(handler)
    wf = Workflow.from_dict({
        "id": "retry", "name": "retry",
        "steps": {"r": {"topic": "job.work",
                        "retry": {"max_retries": 2, "backoff_sec": 0.05, "multiplier": 1.0}}},
    })
    await s.wf_store.put_workflow(wf)
    run = await s.wf_engine.start_run("retry", {})
    run = await s.wait_run(run.run_id, timeout_s=15)
    assert run.status == M.SUCCEEDED, (run.status, run.error)
    assert calls["n"] == 2
    await s.stop()


async def test_full_stack_reconciler_replays_missed_result():
    """Kill the wf service before the result lands; the reconciler must
    replay the terminal job state from the JobStore (crash recovery)."""
    s = Stack()
    gate = asyncio.Event()

    async def handler(ctx):
        await gate.wait()
        return {"late": True}

    await s.start(handler)
    wf = Workflow.from_dict({"id": "cr", "name": "cr", "steps": {"s": {"topic": "job.work"}}})
    await s.wf_store.put_workflow(wf)
    run = await s.wf_engine.start_run("cr", {})
    # plain sleeps (not drain): the in-flight worker task is parked on `gate`
    # and draining would deadlock on it
    await asyncio.sleep(0.1)
    # detach the wf service from the bus AND pause its reconcile loop
    # (simulated crash), then finish the job
    for sub in s.wf_service._subs:
        sub.unsubscribe()
    s.wf_service._task.cancel()
    gate.set()
    await settle(s.bus, rounds=10)
    # scheduler recorded SUCCEEDED in job store; run still RUNNING
    mid = await s.wf_store.get_run(run.run_id)
    assert mid.status == M.RUNNING
    # reconciler replays from job store
    n = await s.wf_service.reconcile_once()
    assert n >= 1
    fin = await s.wf_store.get_run(run.run_id)
    assert fin.status == M.SUCCEEDED
    assert fin.context["steps"]["s"] == {"late": True}
    await s.stop()
