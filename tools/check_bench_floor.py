#!/usr/bin/env python3
"""CI perf-floor gate: fail the build when a bench metric regresses.

Usage::

    python tools/check_bench_floor.py BENCH_JSON [FLOOR_JSON]

``BENCH_JSON`` is the file ``python bench.py --smoke`` wrote (the tool reads
the LAST line that parses as a JSON object, matching the bench's one-line
output contract).  ``FLOOR_JSON`` defaults to ``bench_floor.json`` next to
this repo's root.

The floor file has two sections keyed by bench-JSON metric name:

* ``floors``   — the metric must be **>=** the stored value,
* ``ceilings`` — the metric must be **<=** the stored value (round-trip
  budgets: load-independent, so these are the tight deterministic guards).

One derived metric is computed here rather than read from the doc:
``sharded_vs_single_ratio`` = ``sharded_jobs_per_sec`` /
``sharded_single_jobs_per_sec`` (same-run baseline, so a slow CI box can't
fake a pass or a fail).

Exit status: 0 when every metric holds its bound, 1 on any violation or
missing metric — so ``test.yml`` can gate on it directly.  An r05-style
hot-path regression (2428 → 1646 jobs/s shipped silently) is exactly what
this catches.
"""
from __future__ import annotations

import json
import sys
from pathlib import Path
from typing import Any, Optional


def load_bench_doc(path: str | Path) -> dict[str, Any]:
    """Last JSON-object line of the bench output file."""
    doc: Optional[dict[str, Any]] = None
    for line in Path(path).read_text().splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                parsed = json.loads(line)
            except ValueError:
                continue
            if isinstance(parsed, dict):
                doc = parsed
    if doc is None:
        raise SystemExit(f"no JSON object line found in {path}")
    return doc


def derive(doc: dict[str, Any]) -> dict[str, float]:
    """Metrics the floor file may reference that the bench doc carries
    only in parts."""
    out: dict[str, float] = {}
    sharded = doc.get("sharded_jobs_per_sec")
    single = doc.get("sharded_single_jobs_per_sec")
    if isinstance(sharded, (int, float)) and isinstance(single, (int, float)) and single > 0:
        out["sharded_vs_single_ratio"] = float(sharded) / float(single)
    return out


def check(doc: dict[str, Any], floors_doc: dict[str, Any]) -> list[str]:
    """Returns a list of violation messages (empty = pass)."""
    derived = derive(doc)

    def metric(name: str) -> Optional[float]:
        v = derived.get(name, doc.get(name))
        return float(v) if isinstance(v, (int, float)) else None

    violations: list[str] = []
    rows: list[tuple[str, str, Optional[float], float, bool]] = []
    for name, floor in (floors_doc.get("floors") or {}).items():
        v = metric(name)
        ok = v is not None and v >= float(floor)
        rows.append((name, ">=", v, float(floor), ok))
        if not ok:
            violations.append(
                f"{name} = {v if v is not None else 'MISSING'} "
                f"below floor {floor}"
            )
    for name, ceiling in (floors_doc.get("ceilings") or {}).items():
        v = metric(name)
        ok = v is not None and v <= float(ceiling)
        rows.append((name, "<=", v, float(ceiling), ok))
        if not ok:
            violations.append(
                f"{name} = {v if v is not None else 'MISSING'} "
                f"above ceiling {ceiling}"
            )
    width = max((len(r[0]) for r in rows), default=10)
    for name, op, v, bound, ok in rows:
        shown = f"{v:.2f}" if v is not None else "MISSING"
        print(f"  {'PASS' if ok else 'FAIL'}  {name:<{width}}  "
              f"{shown:>12} {op} {bound}")
    return violations


def main(argv: list[str]) -> int:
    if not argv or len(argv) > 2:
        print(__doc__, file=sys.stderr)
        return 2
    bench_path = argv[0]
    floor_path = argv[1] if len(argv) > 1 else str(
        Path(__file__).resolve().parents[1] / "bench_floor.json"
    )
    doc = load_bench_doc(bench_path)
    floors_doc = json.loads(Path(floor_path).read_text())
    print(f"bench floor check: {bench_path} vs {floor_path}")
    violations = check(doc, floors_doc)
    if violations:
        print("\nPERF FLOOR VIOLATIONS:", file=sys.stderr)
        for v in violations:
            print(f"  - {v}", file=sys.stderr)
        return 1
    print("all perf floors hold")
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
