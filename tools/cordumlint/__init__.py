"""cordumlint — control-plane-aware static analysis for cordum-tpu.

A small AST-based rule engine encoding this codebase's correctness
invariants: deterministic clocks in deadline logic (CL001), no silently
swallowed exceptions (CL002), no blocking calls in async services (CL003),
job-state writes only through the legal-transition table (CL004), bus
subjects from ``protocol/subjects.py`` constants (CL005), and jax
version-gated kwargs only behind the compat shim (CL006).

Run it as ``python -m tools.cordumlint cordum_tpu`` or via ``make lint``.
See ``docs/static_analysis.md`` for the rule catalogue and suppression /
baseline workflow.
"""
from __future__ import annotations

from .core import Finding, LintContext, Rule, all_rules, lint_paths

__version__ = "2.0.0"

__all__ = ["Finding", "LintContext", "Rule", "all_rules", "lint_paths", "__version__"]
