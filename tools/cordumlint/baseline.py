"""Baseline: grandfather existing findings without blessing new ones.

A baseline entry is a content-addressed fingerprint — ``sha1(rule | path |
normalized offending line | occurrence index)`` — so entries survive
unrelated edits (line shifts, renames elsewhere) but invalidate when the
offending line itself changes, forcing a re-decision.  Every entry carries
a human justification; ``--write-baseline`` refuses to run without one.
"""
from __future__ import annotations

import hashlib
import json
from collections import Counter
from pathlib import Path
from typing import Iterable

from .core import Finding

BASELINE_VERSION = 1


def _fingerprints(findings: Iterable[Finding]) -> list[tuple[str, Finding]]:
    """Fingerprint each finding, disambiguating identical lines in one file
    by occurrence order."""
    seen: Counter[str] = Counter()
    out: list[tuple[str, Finding]] = []
    for f in findings:
        key = f"{f.rule_id}|{f.path}|{f.snippet.strip()}"
        occurrence = seen[key]
        seen[key] += 1
        fp = hashlib.sha1(f.fingerprint_input(occurrence).encode()).hexdigest()[:16]
        out.append((fp, f))
    return out


def load(path: Path) -> dict:
    if not path.exists():
        return {"version": BASELINE_VERSION, "entries": {}}
    doc = json.loads(path.read_text(encoding="utf-8"))
    if doc.get("version") != BASELINE_VERSION:
        raise ValueError(
            f"baseline {path} has version {doc.get('version')}, "
            f"expected {BASELINE_VERSION}"
        )
    return doc


def write(path: Path, findings: Iterable[Finding], justification: str) -> int:
    """Record every finding as grandfathered; returns the entry count."""
    entries = {}
    for fp, f in _fingerprints(findings):
        entries[fp] = {
            "rule": f.rule_id,
            "path": f.path,
            "line": f.line,
            "snippet": f.snippet.strip(),
            "justification": justification,
        }
    doc = {"version": BASELINE_VERSION, "entries": entries}
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(doc, indent=2, sort_keys=True) + "\n", encoding="utf-8")
    return len(entries)


def apply(findings: list[Finding], baseline_doc: dict) -> list[Finding]:
    """Mark findings present in the baseline (``baselined=True``) so the
    reporter can separate new violations from grandfathered ones."""
    entries = baseline_doc.get("entries", {})
    out: list[Finding] = []
    for fp, f in _fingerprints(findings):
        if fp in entries:
            f = Finding(**{**f.to_dict(), "baselined": True})
        out.append(f)
    return out
