"""cordumlint CLI.

Exit codes: 0 clean (or everything baselined), 1 active findings,
2 usage / configuration error.
"""
from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from . import __version__, baseline as baseline_mod
from .core import all_rules, lint_paths
from .reporters import json_report, text_report

DEFAULT_BASELINE = "tools/cordumlint/baseline.json"
DEFAULT_CONFIG = "cordumlint.json"


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m tools.cordumlint",
        description="Control-plane-aware static analysis for cordum-tpu.",
    )
    p.add_argument("paths", nargs="*", default=["cordum_tpu"],
                   help="files or directories to lint (default: cordum_tpu)")
    p.add_argument("--root", default=".", help="repo root for relative paths")
    p.add_argument("--config", default=None,
                   help=f"config JSON (default: {DEFAULT_CONFIG} at root if present)")
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--select", default="",
                   help="comma-separated rule ids to run (e.g. CL001,CL006)")
    p.add_argument("--ignore", default="", help="comma-separated rule ids to skip")
    p.add_argument("--baseline", default=None,
                   help=f"baseline JSON path (default: {DEFAULT_BASELINE} at root)")
    p.add_argument("--no-baseline", action="store_true",
                   help="report grandfathered findings as active")
    p.add_argument("--write-baseline", action="store_true",
                   help="record current findings as grandfathered (needs --justification)")
    p.add_argument("--justification", default="",
                   help="why the baselined findings are acceptable (required with --write-baseline)")
    p.add_argument("--show-baselined", action="store_true",
                   help="include baselined findings in the report")
    p.add_argument("--list-rules", action="store_true")
    p.add_argument("--write-obs-inventory", action="store_true",
                   help="regenerate the metric inventory section in "
                        "docs/OBSERVABILITY.md from the code (CL011 checks "
                        "against it)")
    p.add_argument("--version", action="version", version=f"cordumlint {__version__}")
    return p


def _load_config(root: Path, arg: str | None) -> dict:
    path = Path(arg) if arg else root / DEFAULT_CONFIG
    if not path.is_absolute():
        path = root / path
    if path.exists():
        return json.loads(path.read_text(encoding="utf-8"))
    if arg:  # explicitly requested but missing
        raise FileNotFoundError(f"config not found: {path}")
    return {}


def _write_obs_inventory(args, root: Path, config: dict) -> int:
    """Regenerate the CL011-checked metric inventory in docs/OBSERVABILITY.md
    from the same static collection the rule runs."""
    from .core import LintContext, _rel, collect_files
    from .program_rules import (
        INVENTORY_BEGIN, INVENTORY_END, MetricsConformance, render_inventory,
    )

    rule = MetricsConformance((config.get("rules", {}) or {}).get("CL011", {}))
    for f in collect_files(args.paths, root, config.get("exclude", ())):
        try:
            rule.collect(LintContext(f, _rel(f, root), f.read_text(encoding="utf-8")))
        except (SyntaxError, UnicodeDecodeError, OSError):
            continue
    doc = root / rule.doc_rel
    section = render_inventory(rule)
    text = doc.read_text(encoding="utf-8") if doc.exists() else ""
    if INVENTORY_BEGIN in text and INVENTORY_END in text:
        head, rest = text.split(INVENTORY_BEGIN, 1)
        tail = rest.split(INVENTORY_END, 1)[1]
        text = head + section + tail
    else:
        text = text.rstrip() + "\n\n## Metric inventory\n\n" + section + "\n"
    doc.write_text(text, encoding="utf-8")
    print(f"cordumlint: wrote {len(rule.defs)} metric families -> {doc}")
    return 0


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    root = Path(args.root).resolve()

    try:
        config = _load_config(root, args.config)
    except (FileNotFoundError, json.JSONDecodeError) as e:
        print(f"cordumlint: {e}", file=sys.stderr)
        return 2

    if args.list_rules:
        for rule in all_rules(config):
            doc = (rule.__doc__ or "").strip().replace("\n    ", "\n  ")
            print(f"{rule.id} {rule.name}\n  {doc}\n")
        return 0

    if args.write_obs_inventory:
        return _write_obs_inventory(args, root, config)

    select = {s.strip().upper() for s in args.select.split(",") if s.strip()}
    ignore = {s.strip().upper() for s in args.ignore.split(",") if s.strip()}
    result = lint_paths(
        args.paths, root=root, config=config,
        select=select or None, ignore=ignore or None,
    )

    baseline_path = Path(args.baseline) if args.baseline else root / DEFAULT_BASELINE
    if not baseline_path.is_absolute():
        baseline_path = root / baseline_path

    if args.write_baseline:
        if not args.justification.strip():
            print(
                "cordumlint: --write-baseline requires --justification "
                "(why are these findings acceptable?)",
                file=sys.stderr,
            )
            return 2
        n = baseline_mod.write(baseline_path, result.findings, args.justification)
        print(f"cordumlint: baselined {n} finding(s) -> {baseline_path}")
        return 0

    if not args.no_baseline:
        try:
            doc = baseline_mod.load(baseline_path)
        except (ValueError, json.JSONDecodeError) as e:
            print(f"cordumlint: bad baseline: {e}", file=sys.stderr)
            return 2
        result.findings = baseline_mod.apply(result.findings, doc)

    report = text_report if args.format == "text" else json_report
    report(result, stream=sys.stdout, show_baselined=args.show_baselined)

    if result.parse_errors:
        return 2
    active = [f for f in result.findings if not f.baselined]
    return 1 if active else 0
