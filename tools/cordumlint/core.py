"""Rule engine: file walking, AST context, inline suppression, dispatch.

A :class:`Rule` inspects one file at a time through a :class:`LintContext`
(source, parsed AST with parent links, per-line suppression markers) and
yields :class:`Finding` objects.  The engine owns everything rule-agnostic:
collecting Python files, parsing, honoring ``# cordumlint: disable=...``
comments, per-rule enablement, and path allow-lists from the config.
"""
from __future__ import annotations

import ast
import dataclasses
import fnmatch
import re
from pathlib import Path
from typing import Iterable, Iterator, Optional

_DISABLE_RE = re.compile(
    r"#\s*cordumlint:\s*disable=(?P<codes>[A-Za-z0-9,\s]+?|all)\s*(?:--\s*(?P<reason>.*))?$"
)


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at a concrete source location."""

    rule_id: str
    path: str  # repo-relative, forward slashes
    line: int  # 1-based
    col: int  # 0-based
    message: str
    snippet: str = ""
    baselined: bool = False

    def fingerprint_input(self, occurrence: int) -> str:
        """Stable identity: rule + path + normalized line text + occurrence
        index among identical lines — survives unrelated line-number shifts."""
        return f"{self.rule_id}|{self.path}|{self.snippet.strip()}|{occurrence}"

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


class LintContext:
    """Everything a rule needs to inspect one file."""

    def __init__(self, path: Path, rel_path: str, source: str):
        self.path = path
        self.rel_path = rel_path
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=str(path))
        self._parents: dict[ast.AST, ast.AST] = {}
        for parent in ast.walk(self.tree):
            for child in ast.iter_child_nodes(parent):
                self._parents[child] = parent
        self._disabled = self._collect_suppressions()

    # ------------------------------------------------------------------
    def _collect_suppressions(self) -> dict[int, frozenset[str]]:
        """Map line number -> rule ids disabled there (`all` = every rule).
        A marker suppresses its own line and, when the line holds nothing
        but the comment, the line below."""
        disabled: dict[int, frozenset[str]] = {}
        for i, line in enumerate(self.lines, start=1):
            m = _DISABLE_RE.search(line)
            if not m:
                continue
            raw = m.group("codes")
            codes = frozenset(
                c.strip().upper() for c in raw.split(",") if c.strip()
            ) if raw != "all" else frozenset({"ALL"})
            disabled[i] = disabled.get(i, frozenset()) | codes
            if line.strip().startswith("#"):  # standalone marker covers next line
                disabled[i + 1] = disabled.get(i + 1, frozenset()) | codes
        return disabled

    def is_suppressed(self, rule_id: str, line: int) -> bool:
        codes = self._disabled.get(line, frozenset())
        return "ALL" in codes or rule_id.upper() in codes

    # ------------------------------------------------------------------
    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return self._parents.get(node)

    def ancestors(self, node: ast.AST) -> Iterator[ast.AST]:
        cur = self._parents.get(node)
        while cur is not None:
            yield cur
            cur = self._parents.get(cur)

    def enclosing_statement(self, node: ast.AST) -> ast.stmt:
        """Innermost ``ast.stmt`` containing ``node`` (or node itself)."""
        best = node
        for anc in [node, *self.ancestors(node)]:
            if isinstance(anc, ast.stmt):
                best = anc
                break
        return best  # type: ignore[return-value]

    def enclosing_function(self, node: ast.AST) -> Optional[ast.AST]:
        for anc in self.ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return anc
        return None

    def statement_text(self, node: ast.AST) -> str:
        stmt = self.enclosing_statement(node)
        return ast.get_source_segment(self.source, stmt) or ""

    def line_text(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1]
        return ""


class Rule:
    """Base class.  Subclasses set ``id``/``name``/``description`` and
    implement :meth:`check`; ``default_allow_paths`` lists repo-relative
    globs where the rule never fires (the module that legitimately owns
    the flagged construct)."""

    id: str = ""
    name: str = ""
    description: str = ""
    default_allow_paths: tuple[str, ...] = ()

    def __init__(self, options: Optional[dict] = None):
        self.options = options or {}
        self.allow_paths: tuple[str, ...] = tuple(
            self.options.get("allow_paths", self.default_allow_paths)
        )

    def path_allowed(self, rel_path: str) -> bool:
        return any(fnmatch.fnmatch(rel_path, pat) for pat in self.allow_paths)

    def check(self, ctx: LintContext) -> Iterator[Finding]:  # pragma: no cover
        raise NotImplementedError

    def run(self, ctx: LintContext) -> Iterator[Finding]:
        if self.path_allowed(ctx.rel_path):
            return
        for finding in self.check(ctx):
            if not ctx.is_suppressed(self.id, finding.line):
                yield finding

    # -- helpers shared by rules ---------------------------------------
    def finding(self, ctx: LintContext, node: ast.AST, message: str) -> Finding:
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        return Finding(
            rule_id=self.id,
            path=ctx.rel_path,
            line=line,
            col=col,
            message=message,
            snippet=ctx.line_text(line).strip(),
        )


class ProgramRule(Rule):
    """Whole-program rule: sees every file before judging any of them.

    The engine calls :meth:`collect` once per file (in every file, even
    allow-listed ones — the *graph* must be complete; ``allow_paths`` only
    mutes findings reported *in* a path) and then :meth:`finalize` once,
    after the walk, with the repo root and the ``rel_path -> LintContext``
    map so finalize-time findings still honor inline suppressions and can
    carry source snippets.  Findings may point at non-Python files (docs);
    those have no context and cannot be inline-suppressed — fix the doc.
    """

    def collect(self, ctx: LintContext) -> None:  # pragma: no cover
        raise NotImplementedError

    def finalize(
        self, root: Path, contexts: dict[str, "LintContext"]
    ) -> Iterator[Finding]:  # pragma: no cover
        raise NotImplementedError

    def check(self, ctx: LintContext) -> Iterator[Finding]:
        return iter(())

    def run(self, ctx: LintContext) -> Iterator[Finding]:
        self.collect(ctx)
        return iter(())

    # -- helpers shared by program rules --------------------------------
    def finding_at(
        self,
        path: str,
        line: int,
        message: str,
        contexts: dict[str, "LintContext"],
        col: int = 0,
    ) -> Finding:
        ctx = contexts.get(path)
        snippet = ctx.line_text(line).strip() if ctx else ""
        return Finding(
            rule_id=self.id, path=path, line=line, col=col,
            message=message, snippet=snippet,
        )


def all_rules(config: Optional[dict] = None) -> list[Rule]:
    """Instantiate every registered rule honoring per-rule config
    (``{"rules": {"CL001": {"enabled": false, ...}}}``)."""
    from . import rules as rules_mod

    cfg = (config or {}).get("rules", {})
    out: list[Rule] = []
    for cls in rules_mod.RULES:
        opts = cfg.get(cls.id, {})
        if not opts.get("enabled", True):
            continue
        out.append(cls(opts))
    return out


DEFAULT_EXCLUDES = (
    "*/.git/*",
    "*/__pycache__/*",
    "*/node_modules/*",
    "*/.venv/*",
)


def collect_files(paths: Iterable[str], root: Path, excludes: Iterable[str]) -> list[Path]:
    files: list[Path] = []
    patterns = tuple(excludes) + DEFAULT_EXCLUDES
    for p in paths:
        path = (root / p) if not Path(p).is_absolute() else Path(p)
        if path.is_file() and path.suffix == ".py":
            files.append(path)
        elif path.is_dir():
            files.extend(sorted(path.rglob("*.py")))
    out = []
    for f in files:
        rel = _rel(f, root)
        if any(fnmatch.fnmatch(rel, pat) or fnmatch.fnmatch("/" + rel, pat) for pat in patterns):
            continue
        out.append(f)
    return out


def _rel(path: Path, root: Path) -> str:
    try:
        return path.resolve().relative_to(root.resolve()).as_posix()
    except ValueError:
        return path.as_posix()


@dataclasses.dataclass
class LintResult:
    findings: list[Finding]
    files_checked: int
    parse_errors: list[str]


def lint_paths(
    paths: Iterable[str],
    *,
    root: Path,
    config: Optional[dict] = None,
    select: Optional[set[str]] = None,
    ignore: Optional[set[str]] = None,
) -> LintResult:
    """Lint ``paths`` (files or directories) and return every finding."""
    config = config or {}
    rules = all_rules(config)
    if select:
        rules = [r for r in rules if r.id in select]
    if ignore:
        rules = [r for r in rules if r.id not in ignore]
    findings: list[Finding] = []
    parse_errors: list[str] = []
    contexts: dict[str, LintContext] = {}
    files = collect_files(paths, root, config.get("exclude", ()))
    for f in files:
        rel = _rel(f, root)
        try:
            source = f.read_text(encoding="utf-8")
            ctx = LintContext(f, rel, source)
        except (SyntaxError, UnicodeDecodeError, OSError) as e:
            parse_errors.append(f"{rel}: {type(e).__name__}: {e}")
            continue
        contexts[rel] = ctx
        for rule in rules:
            findings.extend(rule.run(ctx))
    for rule in rules:
        if not isinstance(rule, ProgramRule):
            continue
        for fi in rule.finalize(root, contexts):
            if rule.path_allowed(fi.path):
                continue
            fctx = contexts.get(fi.path)
            if fctx is not None and fctx.is_suppressed(rule.id, fi.line):
                continue
            findings.append(fi)
    findings.sort(key=lambda fi: (fi.path, fi.line, fi.col, fi.rule_id))
    return LintResult(findings=findings, files_checked=len(files), parse_errors=parse_errors)
