"""The whole-program rules (CL008-CL011).

Unlike CL001-CL007, these cannot judge a file in isolation: a publish is
only wrong if *no other file* subscribes, a wire-model field is only dead if
*nothing anywhere* reads it.  Each rule collects per-file facts during the
walk and emits findings from ``finalize`` once the fleet-wide picture is
complete (see :class:`~tools.cordumlint.core.ProgramRule`).

Shared annotation grammar (verified, not trusted — see CL008):

``# cordum: guarded-by(<attr>)``
    On an ``async def`` (its line, a decorator line, or a comment line
    directly above): every await-interleaved read-modify-write in the
    method is intentionally serialized by ``self.<attr>`` at a coarser
    level than the method body shows.  On a ``self.X = ...`` line: the
    attribute ``X`` must only be mutated under ``self.<attr>`` — this is
    also the instrumentation source for the runtime sanitizer
    (``cordum_tpu/infra/syncsan.py``).  Either way the named lock must be
    assigned a lock-like object somewhere in the class (or a base class),
    otherwise the *annotation* is the finding.

``# cordum: single-flight``
    On an ``async def`` or ``class``: the method (or every method of the
    class) is only ever executed by one task at a time by construction —
    a loop pump owned by a single background task, a run-once entry point.
    Static analysis cannot verify task topology, so this one is trusted;
    it exists to make the claim grep-able and reviewable.

``# cordum: wire-compat``
    On a wire-model field: the field is intentionally kept although no
    in-tree reader remains (legacy peers still decode it).
"""
from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import Iterator, Optional

from .core import Finding, LintContext, ProgramRule

_ANNOT_RE = re.compile(
    r"#\s*cordum:\s*(?:"
    r"(?P<guarded>guarded-by\((?P<lock>[A-Za-z_][A-Za-z0-9_]*)\))"
    r"|(?P<single>single-flight)"
    r"|(?P<compat>wire-compat)"
    r")"
)


def collect_annotations(ctx: LintContext) -> dict[int, list[tuple[str, Optional[str]]]]:
    """Line -> [(kind, lock_attr_or_None)] for every ``# cordum:`` marker."""
    out: dict[int, list[tuple[str, Optional[str]]]] = {}
    for i, line in enumerate(ctx.lines, start=1):
        if "cordum:" not in line:
            continue
        for m in _ANNOT_RE.finditer(line):
            if m.group("guarded"):
                kind, lock = "guarded-by", m.group("lock")
            elif m.group("single"):
                kind, lock = "single-flight", None
            else:
                kind, lock = "wire-compat", None
            out.setdefault(i, []).append((kind, lock))
    return out


def annotations_on_def(
    ctx: LintContext,
    ann: dict[int, list[tuple[str, Optional[str]]]],
    node: ast.AST,
) -> list[tuple[str, Optional[str], int]]:
    """Annotations attached to a def/class: on its line, a decorator line,
    or the contiguous comment block directly above."""
    first = getattr(node, "lineno", 1)
    decos = getattr(node, "decorator_list", [])
    if decos:
        first = min(first, min(d.lineno for d in decos))
    out: list[tuple[str, Optional[str], int]] = []
    for line in range(first, getattr(node, "lineno", first) + 1):
        for kind, lock in ann.get(line, ()):
            out.append((kind, lock, line))
    line = first - 1
    while line >= 1 and ctx.line_text(line).strip().startswith("#"):
        for kind, lock in ann.get(line, ()):
            out.append((kind, lock, line))
        line -= 1
    return out


def subject_pattern_match(a: str, b: str) -> bool:
    """Do two subject patterns overlap?  ``*`` matches one token, ``>`` the
    rest, on either side (a publish to ``worker.*.jobs`` is heard by a
    subscription to ``worker.*.jobs`` and vice versa)."""
    ta, tb = a.split("."), b.split(".")
    i = 0
    while True:
        if i < len(ta) and ta[i] == ">":
            return len(tb) > i
        if i < len(tb) and tb[i] == ">":
            return len(ta) > i
        if i >= len(ta) or i >= len(tb):
            return len(ta) == len(tb)
        if ta[i] != tb[i] and ta[i] != "*" and tb[i] != "*":
            return False
        i += 1


# ---------------------------------------------------------------------------
# CL008
# ---------------------------------------------------------------------------

_LOCK_CTORS = {
    "Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore",
}


def _self_attr(node: ast.AST) -> Optional[str]:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


class _RaceScan:
    """Single execution-ordered pass over one async function body.

    Tracks, per ``self.*`` attribute (and per ``global``-declared name):
    the await generation + active-lock set at its last read, taint flow
    into locals, and guard frames (attribute read in an ``if``/``while``
    test whose body runs after an await).  A write that is *fed by* or
    *guarded by* a read from an earlier await generation, with no common
    enclosing ``async with`` lock, is a lost-update / check-then-act race.
    """

    def __init__(self, global_names: set[str]):
        self.global_names = global_names
        self.gen = 0  # await generation: bumps at every suspension point
        self.reads: dict[str, tuple[int, frozenset[int]]] = {}
        self.taint: dict[str, set[str]] = {}  # local var -> source attrs
        # guard frames: (attrs read in test, gen at test, lockset at test)
        self.guards: list[tuple[set[str], int, frozenset[int]]] = []
        # attr -> (write_node, read_line, why)
        self.found: dict[str, tuple[ast.AST, int, str]] = {}

    # -- expression side ------------------------------------------------
    def eval_expr(self, node: Optional[ast.AST], lockset: frozenset[int]) -> set[str]:
        """Walk an expression in (approximate) evaluation order; returns the
        set of tracked attrs whose value flows out of it."""
        used: set[str] = set()
        if node is None:
            return used
        for sub in ast.walk(node):
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                continue
            attr = _self_attr(sub)
            if attr is not None and isinstance(sub.ctx, ast.Load):
                used.add(attr)
                self.reads[attr] = (self.gen, lockset)
            elif isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Load):
                if sub.id in self.global_names:
                    key = f"global {sub.id}"
                    used.add(key)
                    self.reads[key] = (self.gen, lockset)
                used |= self.taint.get(sub.id, set())
        # suspension points inside the expression happen before the
        # enclosing statement's store completes
        for sub in ast.walk(node):
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                continue
            if isinstance(sub, (ast.Await, ast.Yield, ast.YieldFrom)):
                self.gen += 1
        return used

    # -- write side ------------------------------------------------------
    def _write_key(self, target: ast.expr) -> Optional[str]:
        attr = _self_attr(target)
        if attr is not None:
            return attr
        if isinstance(target, ast.Name) and target.id in self.global_names:
            return f"global {target.id}"
        return None

    def record_write(
        self,
        key: str,
        node: ast.AST,
        value_used: set[str],
        lockset: frozenset[int],
    ) -> None:
        if key in self.found:
            return
        if key in value_used:
            read = self.reads.get(key)
            if read is not None and read[0] < self.gen and not (read[1] & lockset):
                self.found[key] = (node, read[0], "read-modify-write")
                return
        for guard_attrs, guard_gen, guard_lockset in self.guards:
            if key in guard_attrs and guard_gen < self.gen and not (
                guard_lockset & lockset
            ):
                self.found[key] = (node, guard_gen, "check-then-act")
                return

    # -- statement side --------------------------------------------------
    def walk(self, stmts: list[ast.stmt], lockset: frozenset[int]) -> None:
        for stmt in stmts:
            self.stmt(stmt, lockset)

    def stmt(self, node: ast.stmt, lockset: frozenset[int]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            return
        if isinstance(node, ast.Assign):
            used = self.eval_expr(node.value, lockset)
            for target in node.targets:
                key = self._write_key(target)
                if key is not None:
                    self.record_write(key, node, used, lockset)
                elif isinstance(target, ast.Name):
                    self.taint[target.id] = set(used)
                else:  # self.d[k] = v / self.a.b = v reads the container
                    self.eval_expr(target, lockset)
            return
        if isinstance(node, ast.AugAssign):
            key = self._write_key(node.target)
            used = set() if key is None else {key}
            if key is not None:
                self.reads[key] = (self.gen, lockset)
            used |= self.eval_expr(node.value, lockset)
            if key is not None:
                self.record_write(key, node, used, lockset)
            elif isinstance(node.target, ast.Name):
                self.taint.setdefault(node.target.id, set()).update(used)
            return
        if isinstance(node, ast.AnnAssign):
            used = self.eval_expr(node.value, lockset)
            key = self._write_key(node.target)
            if key is not None:
                self.record_write(key, node, used, lockset)
            elif isinstance(node.target, ast.Name):
                self.taint[node.target.id] = set(used)
            return
        if isinstance(node, (ast.Expr, ast.Return, ast.Raise, ast.Assert, ast.Delete)):
            for child in ast.iter_child_nodes(node):
                self.eval_expr(child, lockset)
            return
        if isinstance(node, (ast.If, ast.While)):
            guard_attrs = self.eval_expr(node.test, lockset)
            tracked = {a for a in guard_attrs if not a.startswith("__")}
            self.guards.append((tracked, self.gen, lockset))
            self.walk(node.body, lockset)
            self.walk(node.orelse, lockset)
            self.guards.pop()
            return
        if isinstance(node, ast.For):
            self.eval_expr(node.iter, lockset)
            self.walk(node.body, lockset)
            self.walk(node.orelse, lockset)
            return
        if isinstance(node, ast.AsyncFor):
            self.eval_expr(node.iter, lockset)
            self.gen += 1  # every iteration suspends
            self.walk(node.body, lockset)
            self.walk(node.orelse, lockset)
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            inner = lockset
            if isinstance(node, ast.AsyncWith):
                self.gen += 1  # __aenter__ awaits
                # `async with self._lock:` / `async with lock:` is mutual
                # exclusion; `async with timeout(...)`/`session.get(...)`
                # (a Call) is not
                if any(
                    isinstance(item.context_expr, (ast.Name, ast.Attribute))
                    for item in node.items
                ):
                    inner = lockset | {id(node)}
            for item in node.items:
                self.eval_expr(item.context_expr, lockset)
            self.walk(node.body, inner)
            return
        if isinstance(node, ast.Try):
            self.walk(node.body, lockset)
            for handler in node.handlers:
                self.walk(handler.body, lockset)
            self.walk(node.orelse, lockset)
            self.walk(node.finalbody, lockset)
            return
        if isinstance(node, ast.Match):
            self.eval_expr(node.subject, lockset)
            for case in node.cases:
                self.walk(case.body, lockset)
            return
        # Pass / Break / Continue / Import / Global / Nonlocal
        return


class AwaitInterleaveRace(ProgramRule):
    """CL008: read-modify-write of ``self.*`` / module state spanning an
    ``await`` with no enclosing ``async with <lock>``.  Every ``await`` is a
    scheduling point: another task can run the same method and interleave,
    so ``read -> await -> write`` on shared state is a lost update (or a
    check-then-act double-fire) waiting for load.  Fix with a lock held
    across the whole read-modify-write, or — when the method is only ever
    run by one task (a loop pump) — declare it with a verified
    ``# cordum: guarded-by(<lock>)`` / ``# cordum: single-flight``
    annotation (see module docstring for the grammar)."""

    id = "CL008"
    name = "await-interleave-race"
    description = (
        "read-modify-write of self.*/module state across an await without "
        "an enclosing async-with lock; fix or annotate "
        "(# cordum: guarded-by(lock) / # cordum: single-flight)"
    )

    def __init__(self, options: Optional[dict] = None):
        super().__init__(options)
        # class name -> set of lock-like attribute names it assigns
        self.class_locks: dict[str, set[str]] = {}
        # class name -> base class simple names
        self.class_bases: dict[str, list[str]] = {}
        # guarded-by annotations to verify: (path, line, class, lock)
        self.annotations: list[tuple[str, int, str, str]] = []
        # deferred race findings: (path, line, col, snippet, message, class, waiver_lock)
        self.candidates: list[tuple[Finding, Optional[str], Optional[str]]] = []

    # -- per-file collection --------------------------------------------
    def _lock_attrs(self, cls: ast.ClassDef) -> set[str]:
        out: set[str] = set()
        for node in ast.walk(cls):
            if not isinstance(node, ast.Assign):
                continue
            value = node.value
            if not (
                isinstance(value, ast.Call)
                and (
                    (isinstance(value.func, ast.Attribute) and value.func.attr in _LOCK_CTORS)
                    or (isinstance(value.func, ast.Name) and value.func.id in _LOCK_CTORS)
                )
            ):
                continue
            for target in node.targets:
                attr = _self_attr(target)
                if attr is not None:
                    out.add(attr)
        return out

    def collect(self, ctx: LintContext) -> None:
        ann = collect_annotations(ctx)
        for cls in ast.walk(ctx.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            self.class_locks.setdefault(cls.name, set()).update(self._lock_attrs(cls))
            self.class_bases.setdefault(cls.name, []).extend(
                b.id for b in cls.bases if isinstance(b, ast.Name)
            )
            cls_single = any(
                kind == "single-flight"
                for kind, _, _ in annotations_on_def(ctx, ann, cls)
            )
            for fn in cls.body:
                if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    self._collect_fn(ctx, ann, cls.name, fn, cls_single)
        # attribute-level guarded-by declarations (`self.x = 0  # cordum:
        # guarded-by(_lock)`) also need their lock verified; find them by
        # line rather than re-walking — only assignment lines count (the
        # def-attached form is handled above, and double-recording it
        # would double-report a bogus lock)
        for line, markers in ann.items():
            if not re.search(r"self\.\w+\s*[:=]", ctx.line_text(line)):
                continue
            for kind, lock in markers:
                if kind != "guarded-by" or lock is None:
                    continue
                owner = self._class_at_line(ctx, line)
                if owner is not None:
                    self.annotations.append((ctx.rel_path, line, owner, lock))
        # module-level async functions
        for fn in ctx.tree.body:
            if isinstance(fn, ast.AsyncFunctionDef):
                self._collect_fn(ctx, ann, "", fn, False)

    def _class_at_line(self, ctx: LintContext, line: int) -> Optional[str]:
        best: Optional[ast.ClassDef] = None
        for cls in ast.walk(ctx.tree):
            if isinstance(cls, ast.ClassDef) and cls.lineno <= line <= (
                cls.end_lineno or cls.lineno
            ):
                if best is None or cls.lineno > best.lineno:
                    best = cls
        return best.name if best is not None else None

    def _collect_fn(
        self,
        ctx: LintContext,
        ann: dict[int, list[tuple[str, Optional[str]]]],
        class_name: str,
        fn: ast.AST,
        cls_single: bool,
    ) -> None:
        markers = annotations_on_def(ctx, ann, fn)
        waiver_lock: Optional[str] = None
        waived = cls_single
        for kind, lock, _line in markers:
            if kind == "single-flight":
                waived = True
            elif kind == "guarded-by" and lock is not None:
                waived = True
                waiver_lock = lock
                self.annotations.append((ctx.rel_path, fn.lineno, class_name, lock))
        if not isinstance(fn, ast.AsyncFunctionDef):
            return
        global_names: set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Global):
                global_names.update(node.names)
        scan = _RaceScan(global_names)
        scan.walk(fn.body, frozenset())
        for attr, (node, read_gen, why) in sorted(
            scan.found.items(), key=lambda kv: kv[1][0].lineno
        ):
            target = attr if attr.startswith("global ") else f"self.{attr}"
            fi = self.finding(
                ctx, node,
                f"{why} race: {target} is read before an await and written "
                f"after it in async {fn.name}() — another task can "
                "interleave at the await and its update is lost; hold one "
                "async-with lock across the read and the write, or declare "
                "the single-writer topology with a verified "
                "`# cordum: guarded-by(<lock>)` / `# cordum: single-flight` "
                "annotation",
            )
            if not waived:
                self.candidates.append((fi, class_name, waiver_lock))

    # -- fleet-wide verification ----------------------------------------
    def _resolve_lock(self, class_name: str, lock: str) -> bool:
        seen: set[str] = set()
        stack = [class_name]
        while stack:
            cls = stack.pop()
            if cls in seen:
                continue
            seen.add(cls)
            if lock in self.class_locks.get(cls, ()):
                return True
            stack.extend(self.class_bases.get(cls, ()))
        return False

    def finalize(
        self, root: Path, contexts: dict[str, LintContext]
    ) -> Iterator[Finding]:
        for fi, class_name, _waiver in self.candidates:
            yield fi
        seen: set[tuple[str, int, str]] = set()
        for path, line, class_name, lock in self.annotations:
            key = (path, line, lock)
            if key in seen:
                continue
            seen.add(key)
            if not class_name or not self._resolve_lock(class_name, lock):
                where = f"class {class_name}" if class_name else "any class"
                yield self.finding_at(
                    path, line,
                    f"annotation error: `# cordum: guarded-by({lock})` names "
                    f"a lock attribute that {where} never assigns a lock-like "
                    "object (asyncio/threading Lock, RLock, Condition, "
                    "Semaphore) — the waiver is unverifiable",
                    contexts,
                )


# ---------------------------------------------------------------------------
# CL009
# ---------------------------------------------------------------------------

_PUBLISH_METHODS = {"publish", "publish_wait", "request"}
_SUBSCRIBE_METHODS = {"subscribe"}
# helper name -> family builder over the constants map; every family the
# helper can produce is listed (the partitioned helpers fall back to the
# parent subject when unsharded)
_HELPER_FAMILIES = {
    "direct_subject": lambda c: ["worker.*.jobs"],
    "gang_subject": lambda c: [c.get("GANG_PREFIX", "sys.job.gang.") + "*"],
    "telemetry_subject": lambda c: [c.get("TELEMETRY_PREFIX", "sys.telemetry.") + "*"],
    "submit_subject": lambda c: [c.get("SUBMIT", ""), c.get("SUBMIT", "") + ".*"],
    "submit_subject_for": lambda c: [c.get("SUBMIT", ""), c.get("SUBMIT", "") + ".*"],
    "result_subject": lambda c: [c.get("RESULT", ""), c.get("RESULT", "") + ".*"],
    "stamped_result_subject": lambda c: [c.get("RESULT", ""), c.get("RESULT", "") + ".*"],
    "cancel_subject": lambda c: [c.get("CANCEL", ""), c.get("CANCEL", "") + ".*"],
}


class _Site:
    __slots__ = ("kind", "symbol", "path", "line", "snippet")

    def __init__(self, kind: str, symbol: tuple[str, str], path: str, line: int,
                 snippet: str):
        self.kind = kind
        self.symbol = symbol  # ("const", NAME) | ("helper", name)
        self.path = path
        self.line = line
        self.snippet = snippet


class SubjectGraphConformance(ProgramRule):
    """CL009: the fleet-wide publish/subscribe graph must close.  Every
    published subject family needs >=1 subscription that can hear it
    (wildcards resolved), every subscription a publisher, and the graph
    must agree with the subject table in ``docs/PROTOCOL.md`` — including
    each row's durable/best-effort column, cross-checked against the
    ``is_durable_subject`` contract.  A publish nobody hears is a silent
    drop (an at-least-once bus redelivers it into the void); a stale doc
    row is how the next integration partner wires the wrong subject.
    Rows whose Purpose contains ``external`` are exempt from the
    in-tree-subscriber requirement."""

    id = "CL009"
    name = "subject-graph-conformance"
    description = (
        "published subjects need an in-tree subscriber (and vice versa); "
        "the graph and durability must match docs/PROTOCOL.md"
    )

    def __init__(self, options: Optional[dict] = None):
        super().__init__(options)
        self.constants: dict[str, str] = {}
        # (rel_path, local name) or ("", attr name) -> bound symbol
        self.aliases: dict[tuple[str, str], tuple[str, str]] = {}
        self.sites: list[_Site] = []
        self.doc_rel = self.options.get("protocol_doc", "docs/PROTOCOL.md")

    # -- collection ------------------------------------------------------
    def collect(self, ctx: LintContext) -> None:
        if ctx.rel_path.endswith("protocol/subjects.py"):
            for node in ctx.tree.body:
                if (
                    isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and isinstance(node.value, ast.Constant)
                    and isinstance(node.value.value, str)
                ):
                    self.constants[node.targets[0].id] = node.value.value
        # alias pass: `self.subject = subj.telemetry_subject(svc)` /
        # `target = subj.RESULT` bind a name that later publish/subscribe
        # calls use — resolve those through a name-keyed alias map
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Assign):
                continue
            symbol = self._symbol(node.value)
            if symbol is None or symbol[0] not in ("const", "helper"):
                continue
            for target in node.targets:
                if isinstance(target, ast.Name):
                    # locals/module names stay file-scoped: `subject` is a
                    # common forwarder parameter name elsewhere
                    self.aliases[(ctx.rel_path, target.id)] = symbol
                elif isinstance(target, ast.Attribute):
                    self.aliases[("", target.attr)] = symbol
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute)):
                continue
            method = node.func.attr
            if method in _PUBLISH_METHODS:
                kind = "publish"
            elif method in _SUBSCRIBE_METHODS:
                kind = "subscribe"
            else:
                continue
            if not node.args:
                continue
            symbol = self._symbol(node.args[0])
            if symbol is None:
                continue
            self.sites.append(_Site(
                kind, symbol, ctx.rel_path, node.lineno,
                ctx.line_text(node.lineno).strip(),
            ))

    def _symbol(self, arg: ast.expr) -> Optional[tuple[str, str]]:
        # subj.CONST / subjects.CONST / bare imported CONST
        if isinstance(arg, ast.Attribute) and arg.attr.isupper():
            return ("const", arg.attr)
        if isinstance(arg, ast.Name) and arg.id.isupper():
            return ("const", arg.id)
        fn = arg.func if isinstance(arg, ast.Call) else None
        name = ""
        if isinstance(fn, ast.Attribute):
            name = fn.attr
        elif isinstance(fn, ast.Name):
            name = fn.id
        if name in _HELPER_FAMILIES:
            return ("helper", name)
        # plain name / attribute: may be an alias bound from a constant or
        # helper elsewhere — resolved against the alias map at finalize
        if isinstance(arg, ast.Name):
            return ("local", arg.id)
        if isinstance(arg, ast.Attribute) and isinstance(arg.value, (ast.Name, ast.Attribute)):
            return ("attr", arg.attr)
        return None  # dynamic subject (forwarders): out of scope

    # -- doc table -------------------------------------------------------
    def _parse_doc(self, root: Path) -> Optional[list[dict]]:
        """Rows of the `## Subjects` table: {patterns, durable, external,
        line}."""
        doc = root / self.doc_rel
        if not doc.exists():
            return None
        rows: list[dict] = []
        in_section = False
        for i, line in enumerate(doc.read_text(encoding="utf-8").splitlines(), 1):
            if line.startswith("#"):
                in_section = line.lstrip("#").strip().lower() == "subjects"
                continue
            if not in_section or not line.startswith("|"):
                continue
            cells = [c.strip() for c in line.strip().strip("|").split("|")]
            if len(cells) < 3 or cells[0].lower() == "subject" or set(cells[0]) <= {"-"}:
                continue
            patterns = []
            for chunk in re.split(r"[,/]", cells[0]):
                subject = chunk.strip().strip("`").strip()
                if not subject:
                    continue
                patterns.append(re.sub(r"<[^>]*>", "*", subject))
            rows.append({
                "patterns": patterns,
                "durable": "durable" in cells[1].lower(),
                "external": "external" in cells[2].lower(),
                "line": i,
                "raw": cells[0],
            })
        return rows

    # -- durability mirror ----------------------------------------------
    def _mirror_is_durable(self, pattern: str) -> bool:
        c = self.constants
        submit = c.get("SUBMIT", "sys.job.submit")
        result = c.get("RESULT", "sys.job.result")
        cancel = c.get("CANCEL", "sys.job.cancel")
        if pattern in (submit, result, c.get("DLQ", "sys.job.dlq"),
                       c.get("TRACE_SPAN", "sys.trace.span"),
                       c.get("STEP_RESULT", "sys.workflow.step.result")):
            return True
        for parent in (submit, result, cancel):
            if pattern.startswith(parent + "."):
                return True
        if pattern.startswith(c.get("JOB_PREFIX", "job.")):
            return True
        if pattern.startswith(c.get("WORKER_PREFIX", "worker.")) and pattern.endswith(".jobs"):
            return True
        return False

    # -- finalize --------------------------------------------------------
    def finalize(
        self, root: Path, contexts: dict[str, LintContext]
    ) -> Iterator[Finding]:
        if not self.constants:
            return  # no subjects.py in the linted set: nothing to resolve
        published: dict[str, _Site] = {}
        subscribed: dict[str, _Site] = {}
        for site in self.sites:
            for pattern in self._resolve(site):
                bucket = published if site.kind == "publish" else subscribed
                bucket.setdefault(pattern, site)
        rows = self._parse_doc(root)
        external = set()
        if rows is not None:
            for row in rows:
                if row["external"]:
                    external.update(row["patterns"])

        for pattern, site in sorted(published.items()):
            if any(subject_pattern_match(pattern, s) for s in subscribed):
                continue
            if any(subject_pattern_match(pattern, e) for e in external):
                continue
            yield self.finding_at(
                site.path, site.line,
                f"orphan publish: nothing in the tree subscribes to "
                f"'{pattern}' — wire up a subscriber, delete the publish, or "
                "document the subject as external in docs/PROTOCOL.md",
                contexts,
            )
        for pattern, site in sorted(subscribed.items()):
            if any(subject_pattern_match(pattern, p) for p in published):
                continue
            if any(subject_pattern_match(pattern, e) for e in external):
                continue
            yield self.finding_at(
                site.path, site.line,
                f"orphan subscription: nothing in the tree publishes to "
                f"'{pattern}' — the handler is dead code or the publisher "
                "was renamed out from under it",
                contexts,
            )

        if rows is None:
            return
        doc_patterns = [p for row in rows for p in row["patterns"]]
        families = set(published) | set(subscribed)
        for pattern, site in sorted({**subscribed, **published}.items()):
            if any(subject_pattern_match(pattern, d) for d in doc_patterns):
                continue
            yield self.finding_at(
                site.path, site.line,
                f"doc drift: subject family '{pattern}' is used here but has "
                f"no row in the {self.doc_rel} Subjects table",
                contexts,
            )
        for row in rows:
            for pattern in row["patterns"]:
                if not row["external"] and not any(
                    subject_pattern_match(pattern, f) for f in families
                ):
                    yield self.finding_at(
                        self.doc_rel, row["line"],
                        f"doc drift: {self.doc_rel} documents subject "
                        f"'{row['raw']}' but nothing in the tree publishes or "
                        "subscribes to it",
                        contexts,
                    )
                    continue
                durable = self._mirror_is_durable(pattern)
                if durable != row["durable"]:
                    actual = "durable" if durable else "best-effort"
                    yield self.finding_at(
                        self.doc_rel, row["line"],
                        f"durability drift: {self.doc_rel} marks "
                        f"'{row['raw']}' as "
                        f"{'durable' if row['durable'] else 'best-effort'} "
                        f"but protocol/subjects.py is_durable_subject says "
                        f"{actual}",
                        contexts,
                    )

    def _resolve(self, site: _Site) -> list[str]:
        kind, name = site.symbol
        if kind == "local":
            alias = self.aliases.get((site.path, name))
            if alias is None:
                return []  # genuinely dynamic (forwarders): out of scope
            kind, name = alias
        elif kind == "attr":
            alias = self.aliases.get(("", name))
            if alias is None:
                return []
            kind, name = alias
        if kind == "const":
            value = self.constants.get(name)
            return [value] if value else []
        return [p for p in _HELPER_FAMILIES[name](self.constants) if p]


# ---------------------------------------------------------------------------
# CL010
# ---------------------------------------------------------------------------


class WireModelDrift(ProgramRule):
    """CL010: wire-model fields that are encoded but never read anywhere in
    the tree (dead weight on every packet, and a trap: readers assume the
    writer keeps populating it), or read but never set (always the default —
    the reader is testing a value nobody produces).  Liveness is name-based
    across the whole tree: an attribute load, ``pkt["field"]`` /
    ``.get("field")`` subscript, or ``getattr`` read keeps a field alive.
    Fields intentionally kept for legacy peers carry
    ``# cordum: wire-compat``.  Also cross-checks msgpack record keys:
    a key subscripted out of an ``unpack_record()`` result that no literal
    ``pack_record({...})`` site ever writes is a reader expecting a record
    shape no writer produces."""

    id = "CL010"
    name = "wire-model-drift"
    description = (
        "protocol/types.py dataclass fields encoded-but-never-read / "
        "read-but-never-set, and unpack_record keys no pack_record writes"
    )

    def __init__(self, options: Optional[dict] = None):
        super().__init__(options)
        self.types_glob = self.options.get("types_path", "*protocol/types.py")
        # class -> [(field, line, path, compat)]
        self.fields: dict[str, list[tuple[str, int, str, bool]]] = {}
        self.field_order: dict[str, list[str]] = {}
        self.reads: set[str] = set()
        self.stores: set[str] = set()
        self.ctor_stores: dict[str, set[str]] = {}
        self.pack_keys: set[str] = set()
        self.opaque_pack = False
        self.unpack_reads: list[tuple[str, str, int]] = []  # key, path, line

    # -- collection ------------------------------------------------------
    def collect(self, ctx: LintContext) -> None:
        import fnmatch as _fn

        if _fn.fnmatch(ctx.rel_path, self.types_glob):
            self._collect_models(ctx)
        self._collect_usage(ctx)
        self._collect_records(ctx)

    def _collect_models(self, ctx: LintContext) -> None:
        ann = collect_annotations(ctx)
        for cls in ctx.tree.body:
            if not isinstance(cls, ast.ClassDef):
                continue
            is_dc = any(
                (isinstance(d, ast.Name) and d.id == "dataclass")
                or (isinstance(d, ast.Call) and isinstance(d.func, ast.Name)
                    and d.func.id == "dataclass")
                or (isinstance(d, ast.Attribute) and d.attr == "dataclass")
                or (isinstance(d, ast.Call) and isinstance(d.func, ast.Attribute)
                    and d.func.attr == "dataclass")
                for d in cls.decorator_list
            )
            if not is_dc:
                continue
            fields: list[tuple[str, int, str, bool]] = []
            order: list[str] = []
            for stmt in cls.body:
                if not (isinstance(stmt, ast.AnnAssign) and isinstance(stmt.target, ast.Name)):
                    continue
                anno = stmt.annotation
                anno_name = ""
                if isinstance(anno, ast.Subscript) and isinstance(anno.value, ast.Name):
                    anno_name = anno.value.id
                elif isinstance(anno, ast.Name):
                    anno_name = anno.id
                if anno_name == "ClassVar":
                    continue
                name = stmt.target.id
                if name.startswith("_"):
                    continue
                compat = any(
                    kind == "wire-compat" for kind, _ in ann.get(stmt.lineno, ())
                ) or any(
                    kind == "wire-compat" for kind, _ in ann.get(stmt.lineno - 1, ())
                    if ctx.line_text(stmt.lineno - 1).strip().startswith("#")
                )
                fields.append((name, stmt.lineno, ctx.rel_path, compat))
                order.append(name)
            if fields:
                self.fields[cls.name] = fields
                self.field_order[cls.name] = order

    def _collect_usage(self, ctx: LintContext) -> None:
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Attribute):
                if isinstance(node.ctx, ast.Load):
                    self.reads.add(node.attr)
                elif isinstance(node.ctx, ast.Store):
                    self.stores.add(node.attr)
            elif isinstance(node, ast.Subscript):
                sl = node.slice
                if isinstance(sl, ast.Constant) and isinstance(sl.value, str):
                    if isinstance(node.ctx, ast.Store):
                        self.stores.add(sl.value)
                    else:
                        self.reads.add(sl.value)
            elif isinstance(node, ast.Call):
                fn = node.func
                fname = fn.id if isinstance(fn, ast.Name) else (
                    fn.attr if isinstance(fn, ast.Attribute) else ""
                )
                if fname in ("get", "getattr", "pop") and node.args:
                    arg0 = node.args[1] if fname == "getattr" and len(node.args) > 1 \
                        else node.args[0]
                    if isinstance(arg0, ast.Constant) and isinstance(arg0.value, str):
                        self.reads.add(arg0.value)
                if fname == "setattr" and len(node.args) > 1:
                    arg1 = node.args[1]
                    if isinstance(arg1, ast.Constant) and isinstance(arg1.value, str):
                        self.stores.add(arg1.value)
                for kw in node.keywords:
                    if kw.arg is not None:
                        self.stores.add(kw.arg)
                if isinstance(fn, ast.Name) and node.args:
                    self.ctor_stores.setdefault(fn.id, set()).update(
                        str(i) for i in range(len(node.args))
                    )
            elif isinstance(node, ast.Dict):
                for key in node.keys:
                    if isinstance(key, ast.Constant) and isinstance(key.value, str):
                        self.stores.add(key.value)

    def _collect_records(self, ctx: LintContext) -> None:
        def fname(call: ast.Call) -> str:
            fn = call.func
            if isinstance(fn, ast.Name):
                return fn.id
            if isinstance(fn, ast.Attribute):
                return fn.attr
            return ""

        for scope in ast.walk(ctx.tree):
            if not isinstance(scope, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Module)):
                continue
            body_nodes = [
                n for n in ast.walk(scope)
                if not isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
                or n is scope
            ]
            unpacked: set[str] = set()
            dict_lits: dict[str, set[str]] = {}
            for node in body_nodes:
                if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                        isinstance(node.targets[0], ast.Name):
                    var = node.targets[0].id
                    if isinstance(node.value, ast.Call) and fname(node.value) == "unpack_record":
                        unpacked.add(var)
                    elif isinstance(node.value, ast.Dict):
                        keys = {
                            k.value for k in node.value.keys
                            if isinstance(k, ast.Constant) and isinstance(k.value, str)
                        }
                        if keys:
                            dict_lits[var] = keys
            for node in body_nodes:
                if isinstance(node, ast.Call) and fname(node) == "pack_record" and node.args:
                    arg = node.args[0]
                    if isinstance(arg, ast.Dict):
                        for k in arg.keys:
                            if isinstance(k, ast.Constant) and isinstance(k.value, str):
                                self.pack_keys.add(k.value)
                            else:
                                self.opaque_pack = True
                    elif isinstance(arg, ast.Name) and arg.id in dict_lits:
                        self.pack_keys.update(dict_lits[arg.id])
                    else:
                        self.opaque_pack = True
                if (
                    isinstance(node, ast.Subscript)
                    and isinstance(node.value, ast.Name)
                    and node.value.id in unpacked
                    and isinstance(node.slice, ast.Constant)
                    and isinstance(node.slice.value, str)
                    and isinstance(node.ctx, ast.Load)
                ):
                    self.unpack_reads.append(
                        (node.slice.value, ctx.rel_path, node.lineno)
                    )

    # -- finalize --------------------------------------------------------
    def finalize(
        self, root: Path, contexts: dict[str, LintContext]
    ) -> Iterator[Finding]:
        for cls, fields in sorted(self.fields.items()):
            order = self.field_order[cls]
            positional = {
                order[int(i)]
                for i in self.ctor_stores.get(cls, ())
                if int(i) < len(order)
            }
            for name, line, path, compat in fields:
                if compat:
                    continue
                if name not in self.reads:
                    yield self.finding_at(
                        path, line,
                        f"dead wire field: {cls}.{name} is encoded on every "
                        "packet but nothing in the tree ever reads it — "
                        "prune it (legacy decode stays tolerant via "
                        "from_dict) or mark it `# cordum: wire-compat`",
                        contexts,
                    )
                elif name not in self.stores and name not in positional:
                    yield self.finding_at(
                        path, line,
                        f"never-set wire field: {cls}.{name} is read but no "
                        "constructor call, attribute write, or dict literal "
                        "anywhere sets it — readers always see the default",
                        contexts,
                    )
        if not self.opaque_pack and self.pack_keys:
            seen: set[str] = set()
            for key, path, line in sorted(self.unpack_reads):
                if key in self.pack_keys or key in seen:
                    continue
                seen.add(key)
                yield self.finding_at(
                    path, line,
                    f"record-key drift: this unpack_record() reader indexes "
                    f"['{key}'] but no pack_record() writer in the tree ever "
                    "writes that key",
                    contexts,
                )


# ---------------------------------------------------------------------------
# CL011
# ---------------------------------------------------------------------------

_METRIC_CTORS = {"Counter", "Gauge", "Histogram"}
_METRIC_WRITES = {"inc", "observe", "set", "dec"}
_NON_LABEL_KWARGS = {"exemplar", "amount", "value"}

INVENTORY_BEGIN = "<!-- cordumlint: metrics-inventory begin -->"
INVENTORY_END = "<!-- cordumlint: metrics-inventory end -->"


class MetricsConformance(ProgramRule):
    """CL011: every ``cordum_*`` metric family must be written with one
    consistent label schema at every call site (two sites disagreeing on
    label names silently split one family into disjoint series — dashboards
    aggregate half the truth) and must be documented in
    ``docs/OBSERVABILITY.md``, whose generated inventory table
    (``python -m tools.cordumlint --write-obs-inventory``) must list the
    exact label set the code uses."""

    id = "CL011"
    name = "metrics-conformance"
    description = (
        "cordum_* metrics need one label schema across all call sites and a "
        "matching row/mention in docs/OBSERVABILITY.md"
    )

    def __init__(self, options: Optional[dict] = None):
        super().__init__(options)
        self.doc_rel = self.options.get("observability_doc", "docs/OBSERVABILITY.md")
        # metric name -> (type, help, path, line)
        self.defs: dict[str, tuple[str, str, str, int]] = {}
        # handle attr/var name -> metric name
        self.handles: dict[str, str] = {}
        # raw write sites: (recv_key_or_name, labels_or_None, path, line)
        self.raw_sites: list[tuple[Optional[str], Optional[frozenset[str]], str, int]] = []

    def collect(self, ctx: LintContext) -> None:
        # pass 1: definitions + handle bindings (file-order independent)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            ctor = self._ctor_name(node)
            if ctor is None or not node.args:
                continue
            arg0 = node.args[0]
            if not (isinstance(arg0, ast.Constant) and isinstance(arg0.value, str)
                    and arg0.value.startswith("cordum_")):
                continue
            help_ = ""
            if len(node.args) > 1 and isinstance(node.args[1], ast.Constant) \
                    and isinstance(node.args[1].value, str):
                help_ = node.args[1].value
            for kw in node.keywords:
                if kw.arg == "help_" and isinstance(kw.value, ast.Constant) \
                        and isinstance(kw.value.value, str):
                    help_ = kw.value.value
            if arg0.value not in self.defs:
                self.defs[arg0.value] = (ctor.lower(), help_, ctx.rel_path, node.lineno)
            parent = ctx.parent(node)
            if isinstance(parent, ast.Assign):
                for target in parent.targets:
                    if isinstance(target, ast.Attribute):
                        self.handles[target.attr] = arg0.value
                    elif isinstance(target, ast.Name):
                        self.handles[target.id] = arg0.value
        # pass 2: write sites
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute)):
                continue
            if node.func.attr not in _METRIC_WRITES:
                continue
            recv = node.func.value
            key: Optional[str] = None
            if isinstance(recv, ast.Call):
                ctor = self._ctor_name(recv)
                if ctor and recv.args and isinstance(recv.args[0], ast.Constant):
                    key = str(recv.args[0].value)
            elif isinstance(recv, ast.Attribute):
                key = recv.attr
            elif isinstance(recv, ast.Name):
                key = recv.id
            if key is None:
                continue
            labels: Optional[frozenset[str]] = frozenset(
                kw.arg for kw in node.keywords
                if kw.arg is not None and kw.arg not in _NON_LABEL_KWARGS
            )
            if any(kw.arg is None for kw in node.keywords):
                labels = None  # **labels passthrough: schema unknown here
            self.raw_sites.append((key, labels, ctx.rel_path, node.lineno))

    def _ctor_name(self, node: ast.Call) -> Optional[str]:
        fn = node.func
        if isinstance(fn, ast.Name) and fn.id in _METRIC_CTORS:
            return fn.id
        if isinstance(fn, ast.Attribute) and fn.attr in _METRIC_CTORS:
            return fn.attr
        return None

    # -- shared with the inventory generator -----------------------------
    def resolved_schemas(self) -> dict[str, dict[frozenset[str], list[tuple[str, int]]]]:
        """metric name -> label-set -> [(path, line)] across resolved write
        sites (sites whose receiver isn't a known handle are skipped —
        they're some other object's .set/.inc)."""
        out: dict[str, dict[frozenset[str], list[tuple[str, int]]]] = {}
        for key, labels, path, line in self.raw_sites:
            name = key if key in self.defs else self.handles.get(key or "")
            if name is None or name not in self.defs:
                continue
            if labels is None:
                continue
            out.setdefault(name, {}).setdefault(labels, []).append((path, line))
        return out

    def inventory_rows(self) -> list[tuple[str, str, str, str]]:
        """(name, type, labels-cell, help) rows for the generated table."""
        schemas = self.resolved_schemas()
        rows = []
        for name in sorted(self.defs):
            type_, help_, _p, _l = self.defs[name]
            label_union: set[str] = set()
            for labels in schemas.get(name, ()):  # post-CL011 there is one
                label_union |= labels
            cell = ", ".join(sorted(label_union)) if label_union else "—"
            rows.append((name, type_, cell, help_))
        return rows

    def finalize(
        self, root: Path, contexts: dict[str, LintContext]
    ) -> Iterator[Finding]:
        schemas = self.resolved_schemas()
        for name, by_schema in sorted(schemas.items()):
            if len(by_schema) <= 1:
                continue
            modal = max(by_schema.items(), key=lambda kv: len(kv[1]))[0]
            for labels, sites in sorted(by_schema.items(), key=lambda kv: sorted(kv[0])):
                if labels == modal:
                    continue
                path, line = sites[0]
                yield self.finding_at(
                    path, line,
                    f"label-schema drift: {name} is written here with labels "
                    f"{{{', '.join(sorted(labels)) or 'none'}}} but its other "
                    f"call sites use {{{', '.join(sorted(modal)) or 'none'}}} "
                    "— one family, one schema",
                    contexts,
                )
        doc = root / self.doc_rel
        if not doc.exists():
            return
        text = doc.read_text(encoding="utf-8")
        inventory = None
        if INVENTORY_BEGIN in text and INVENTORY_END in text:
            inventory = text.split(INVENTORY_BEGIN, 1)[1].split(INVENTORY_END, 1)[0]
        for name, (_type, _help, path, line) in sorted(self.defs.items()):
            if name not in text:
                yield self.finding_at(
                    path, line,
                    f"undocumented metric: {name} is not mentioned anywhere "
                    f"in {self.doc_rel} — document it (and regenerate the "
                    "inventory: python -m tools.cordumlint "
                    "--write-obs-inventory)",
                    contexts,
                )
        if inventory is not None:
            documented: dict[str, set[str]] = {}
            for line_text in inventory.splitlines():
                if not line_text.startswith("|"):
                    continue
                cells = [c.strip() for c in line_text.strip().strip("|").split("|")]
                if len(cells) < 3 or cells[0].lower() == "metric" or set(cells[0]) <= {"-"}:
                    continue
                mname = cells[0].strip("`")
                labels = {
                    s.strip() for s in cells[2].split(",")
                    if s.strip() and s.strip() != "—"
                }
                documented[mname] = labels
            for name, type_, cell, _help in self.inventory_rows():
                want = {s.strip() for s in cell.split(",") if s.strip() and s.strip() != "—"}
                if name not in documented:
                    _t, _h, path, line = self.defs[name]
                    yield self.finding_at(
                        path, line,
                        f"inventory drift: {name} is missing from the "
                        f"generated metric inventory in {self.doc_rel}; "
                        "regenerate it (python -m tools.cordumlint "
                        "--write-obs-inventory)",
                        contexts,
                    )
                elif documented[name] != want:
                    _t, _h, path, line = self.defs[name]
                    yield self.finding_at(
                        path, line,
                        f"inventory drift: {self.doc_rel} lists {name} with "
                        f"labels {{{', '.join(sorted(documented[name])) or 'none'}}} "
                        f"but the code writes {{{', '.join(sorted(want)) or 'none'}}}; "
                        "regenerate the inventory",
                        contexts,
                    )
            stale = set(documented) - set(self.defs)
            if stale:
                yield self.finding_at(
                    self.doc_rel, 1,
                    "inventory drift: the generated inventory lists metrics "
                    f"the code no longer defines: {', '.join(sorted(stale))}; "
                    "regenerate it",
                    contexts,
                )


def render_inventory(rule: MetricsConformance) -> str:
    lines = [
        INVENTORY_BEGIN,
        "<!-- generated by `python -m tools.cordumlint --write-obs-inventory`;",
        "     do not edit by hand — CL011 fails lint when this table drifts -->",
        "",
        "| Metric | Type | Labels | Help |",
        "|---|---|---|---|",
    ]
    for name, type_, labels, help_ in rule.inventory_rows():
        lines.append(f"| `{name}` | {type_} | {labels} | {help_} |")
    lines.append("")
    lines.append(INVENTORY_END)
    return "\n".join(lines)


PROGRAM_RULES = (
    AwaitInterleaveRace,
    SubjectGraphConformance,
    WireModelDrift,
    MetricsConformance,
)
