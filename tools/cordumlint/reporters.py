"""Reporters: human text and machine JSON."""
from __future__ import annotations

import json
from collections import Counter
from typing import IO

from .core import LintResult


def text_report(
    result: LintResult, *, stream: IO[str], show_baselined: bool = False
) -> None:
    shown = [
        f for f in result.findings if show_baselined or not f.baselined
    ]
    for f in shown:
        tag = " [baselined]" if f.baselined else ""
        stream.write(f"{f.path}:{f.line}:{f.col + 1}: {f.rule_id}{tag} {f.message}\n")
        if f.snippet:
            stream.write(f"    {f.snippet}\n")
    active = [f for f in result.findings if not f.baselined]
    baselined = len(result.findings) - len(active)
    by_rule = Counter(f.rule_id for f in active)
    summary = ", ".join(f"{r}: {n}" for r, n in sorted(by_rule.items())) or "clean"
    stream.write(
        f"\n{len(active)} finding(s) in {result.files_checked} file(s)"
        f" ({baselined} baselined) — {summary}\n"
    )
    for err in result.parse_errors:
        stream.write(f"parse error: {err}\n")


def json_report(
    result: LintResult, *, stream: IO[str], show_baselined: bool = False
) -> None:
    active = [f for f in result.findings if not f.baselined]
    doc = {
        "files_checked": result.files_checked,
        "findings": [
            f.to_dict()
            for f in result.findings
            if show_baselined or not f.baselined
        ],
        "summary": dict(Counter(f.rule_id for f in active)),
        "active_count": len(active),
        "baselined_count": len(result.findings) - len(active),
        "parse_errors": result.parse_errors,
    }
    json.dump(doc, stream, indent=2, sort_keys=True)
    stream.write("\n")
