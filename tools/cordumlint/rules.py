"""The six cordum-tpu rules.  Each encodes an invariant this control plane
depends on; the docstrings carry the rationale shown in ``--list-rules``.
"""
from __future__ import annotations

import ast
import re
from typing import Iterator

from .core import Finding, LintContext, Rule

# ---------------------------------------------------------------------------
# CL001
# ---------------------------------------------------------------------------

_DEADLINE_WORDS = re.compile(
    r"timeout|deadline|ttl|lease|expir|cutoff|stale|breaker|window|elapsed"
    r"|backoff|retry|renew|interval|latency|heartbeat",
    re.IGNORECASE,
)


class NoWallClockDeadline(Rule):
    """CL001: wall-clock ``time.time()`` in timeout/lease/TTL/deadline
    arithmetic.  NTP steps and clock skew make wall time go backwards;
    lease math built on it either never expires or expires instantly.
    Use ``time.monotonic()`` for in-process durations, or the blessed
    ``cordum_tpu.utils.ids.now_us/now_ms`` helpers when comparing against
    persisted cross-process timestamps (the job store's clock)."""

    id = "CL001"
    name = "no-wall-clock-deadline"
    description = (
        "time.time() forbidden in timeout/lease/TTL arithmetic; use "
        "time.monotonic() or utils.ids.now_us/now_ms"
    )
    # utils/ids.py IS the blessed wall-clock source for persisted timestamps
    default_allow_paths = ("cordum_tpu/utils/ids.py", "*/utils/ids.py")

    # modules whose whole purpose is deadline/lease arithmetic: every
    # wall-clock call there is a violation, keyword context or not
    default_strict_paths = (
        "cordum_tpu/controlplane/scheduler/reconciler.py",
        "cordum_tpu/controlplane/scheduler/safety_client.py",
        "cordum_tpu/infra/registry.py",
        "cordum_tpu/infra/locks.py",
        "cordum_tpu/infra/kv.py",
    )

    def _is_wall_clock_call(self, node: ast.Call) -> bool:
        fn = node.func
        if isinstance(fn, ast.Attribute) and fn.attr in ("time", "time_ns"):
            return isinstance(fn.value, ast.Name) and fn.value.id == "time"
        return False

    def check(self, ctx: LintContext) -> Iterator[Finding]:
        strict = ctx.rel_path in tuple(
            self.options.get("strict_paths", self.default_strict_paths)
        )
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call) and self._is_wall_clock_call(node)):
                continue
            stmt_text = ctx.statement_text(node)
            if strict or _DEADLINE_WORDS.search(stmt_text):
                yield self.finding(
                    ctx,
                    node,
                    "wall-clock time.time() in deadline/lease/timeout "
                    "arithmetic; use time.monotonic() for in-process "
                    "durations or utils.ids.now_us/now_ms for persisted "
                    "timestamps",
                )


# ---------------------------------------------------------------------------
# CL002
# ---------------------------------------------------------------------------

_BROAD_NAMES = {"Exception", "BaseException"}


class NoSilentSwallow(Rule):
    """CL002: broad ``except`` whose body neither logs, re-raises, nor
    returns a fallback value.  This is the ``bench.py`` class of bug: a
    crashed JAX child reported a partial metric as if healthy.  In a
    fail-closed control plane a swallowed error IS a wrong answer."""

    id = "CL002"
    name = "no-silent-swallow"
    description = (
        "broad `except Exception` with a pass/continue/bare-return body; "
        "log, re-raise, or return an explicit fallback"
    )

    def _is_broad(self, handler: ast.ExceptHandler) -> bool:
        t = handler.type
        if t is None:
            return True  # bare except:
        names = []
        if isinstance(t, ast.Tuple):
            names = [e.id for e in t.elts if isinstance(e, ast.Name)]
        elif isinstance(t, ast.Name):
            names = [t.id]
        return any(n in _BROAD_NAMES for n in names)

    def _is_silent_stmt(self, stmt: ast.stmt) -> bool:
        if isinstance(stmt, (ast.Pass, ast.Continue, ast.Break)):
            return True
        if isinstance(stmt, ast.Return):
            return stmt.value is None or (
                isinstance(stmt.value, ast.Constant) and stmt.value.value is None
            )
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Constant):
            return True  # docstring / ellipsis
        return False

    def check(self, ctx: LintContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not self._is_broad(node):
                continue
            if all(self._is_silent_stmt(s) for s in node.body):
                yield self.finding(
                    ctx,
                    node,
                    "broad except swallows the error silently; log it with "
                    "context, re-raise, or return an explicit fallback "
                    "(narrow to the exceptions you actually expect)",
                )


# ---------------------------------------------------------------------------
# CL003
# ---------------------------------------------------------------------------

_BLOCKING_ATTR_CALLS = {
    ("time", "sleep"): "await asyncio.sleep(...)",
    ("requests", "get"): "aiohttp (or asyncio.to_thread)",
    ("requests", "post"): "aiohttp (or asyncio.to_thread)",
    ("requests", "put"): "aiohttp (or asyncio.to_thread)",
    ("requests", "delete"): "aiohttp (or asyncio.to_thread)",
    ("requests", "request"): "aiohttp (or asyncio.to_thread)",
    ("urllib.request", "urlopen"): "aiohttp (or asyncio.to_thread)",
    ("subprocess", "run"): "asyncio.create_subprocess_exec",
    ("subprocess", "call"): "asyncio.create_subprocess_exec",
    ("subprocess", "check_call"): "asyncio.create_subprocess_exec",
    ("subprocess", "check_output"): "asyncio.create_subprocess_exec",
    ("socket", "create_connection"): "asyncio.open_connection",
}


class NoBlockingInAsync(Rule):
    """CL003: blocking calls (``time.sleep``, sync HTTP, ``subprocess``,
    ``open``) inside ``async def`` bodies.  One blocked event loop stalls
    every job the service is carrying — at 1k scheduled jobs/sec a 100 ms
    sync read is 100 dropped scheduling slots."""

    id = "CL003"
    name = "no-blocking-in-async"
    description = (
        "time.sleep / sync HTTP / blocking file IO inside async def; use "
        "asyncio.sleep, aiohttp, or asyncio.to_thread"
    )

    def _async_owner(self, ctx: LintContext, node: ast.AST):
        """The async function whose *runtime* body contains node (stops at
        the nearest enclosing def — nested sync helpers run out-of-line)."""
        for anc in ctx.ancestors(node):
            if isinstance(anc, ast.FunctionDef):
                return None
            if isinstance(anc, ast.AsyncFunctionDef):
                return anc
        return None

    def check(self, ctx: LintContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            owner = self._async_owner(ctx, node)
            if owner is None:
                continue
            hint = self._blocking_hint(node)
            if hint:
                yield self.finding(
                    ctx,
                    node,
                    f"blocking call in async def {owner.name}(); use {hint}",
                )

    def _blocking_hint(self, node: ast.Call) -> str:
        fn = node.func
        if isinstance(fn, ast.Name) and fn.id == "open":
            return "asyncio.to_thread(...) or load outside the event loop"
        if isinstance(fn, ast.Attribute) and isinstance(fn.value, ast.Name):
            return _BLOCKING_ATTR_CALLS.get((fn.value.id, fn.attr), "")
        if isinstance(fn, ast.Attribute) and isinstance(fn.value, ast.Attribute):
            base = fn.value
            if isinstance(base.value, ast.Name):
                dotted = f"{base.value.id}.{base.attr}"
                return _BLOCKING_ATTR_CALLS.get((dotted, fn.attr), "")
        return ""


# ---------------------------------------------------------------------------
# CL004
# ---------------------------------------------------------------------------

_JOB_STATES = {
    "PENDING", "APPROVAL_REQUIRED", "SCHEDULED", "DISPATCHED", "RUNNING",
    "SUCCEEDED", "FAILED", "CANCELLED", "TIMEOUT", "DENIED",
}


class StateTransitionDiscipline(Rule):
    """CL004: raw string writes to a job ``state`` field outside the
    transition table's home.  Every state change must flow through
    ``JobStore.set_state`` (which validates against
    ``protocol.types.ALLOWED_TRANSITIONS``) — a raw write can resurrect a
    terminal job or skip the approval gate."""

    id = "CL004"
    name = "state-transition-discipline"
    description = (
        "job state assignments outside protocol/types.py / infra/jobstore.py "
        "must use JobStore.set_state, not raw string writes"
    )
    default_allow_paths = (
        "cordum_tpu/protocol/types.py",
        "cordum_tpu/infra/jobstore.py",
    )

    def _is_state_target(self, target: ast.expr) -> bool:
        if isinstance(target, ast.Attribute) and target.attr == "state":
            return True
        if isinstance(target, ast.Subscript):
            sl = target.slice
            return isinstance(sl, ast.Constant) and sl.value == "state"
        return False

    def _is_raw_state_value(self, value: ast.expr) -> bool:
        return isinstance(value, ast.Constant) and value.value in _JOB_STATES

    def check(self, ctx: LintContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            targets: list[ast.expr] = []
            value: ast.expr | None = None
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                targets, value = [node.target], node.value
            elif isinstance(node, ast.Dict):
                for k, v in zip(node.keys, node.values):
                    if (
                        isinstance(k, ast.Constant)
                        and k.value == "state"
                        and self._is_raw_state_value(v)
                    ):
                        yield self.finding(
                            ctx, v,
                            "raw job-state string literal; pass a JobState "
                            "member so the transition table stays the single "
                            "source of truth",
                        )
                continue
            if value is None or not self._is_raw_state_value(value):
                continue
            for t in targets:
                if self._is_state_target(t):
                    yield self.finding(
                        ctx, node,
                        "raw job-state write bypasses the legal-transition "
                        "table; use JobStore.set_state(job_id, JobState.X)",
                    )


# ---------------------------------------------------------------------------
# CL005
# ---------------------------------------------------------------------------

_BUS_METHODS = {"publish", "subscribe", "request", "publish_wait", "unsubscribe"}
_SUBJECT_PREFIXES = ("sys.", "worker.", "job.")


class SubjectLiterals(Rule):
    """CL005: ad-hoc bus subject strings.  Subjects are wire protocol: a
    typo'd literal routes jobs nowhere (silently, with an at-least-once bus
    redelivering into the void).  They must come from
    ``protocol/subjects.py`` constants or its ``direct_subject()`` helper."""

    id = "CL005"
    name = "subject-literals"
    description = (
        "bus subjects must come from protocol/subjects.py constants, not "
        "ad-hoc string literals / f-strings"
    )
    default_allow_paths = ("cordum_tpu/protocol/subjects.py",)

    def _literal_subject(self, arg: ast.expr) -> bool:
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            return arg.value.startswith(_SUBJECT_PREFIXES)
        if isinstance(arg, ast.JoinedStr):
            head = arg.values[0] if arg.values else None
            return (
                isinstance(head, ast.Constant)
                and isinstance(head.value, str)
                and head.value.startswith(_SUBJECT_PREFIXES)
            )
        return False

    def check(self, ctx: LintContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call):
                fn = node.func
                if (
                    isinstance(fn, ast.Attribute)
                    and fn.attr in _BUS_METHODS
                    and node.args
                    and self._literal_subject(node.args[0])
                ):
                    yield self.finding(
                        ctx, node.args[0],
                        "ad-hoc subject literal in bus call; use a "
                        "protocol.subjects constant (or direct_subject())",
                    )
            elif isinstance(node, ast.JoinedStr):
                # f"worker.{id}.jobs" built anywhere = re-implemented router
                parts = [
                    v.value for v in node.values
                    if isinstance(v, ast.Constant) and isinstance(v.value, str)
                ]
                if parts and parts[0].startswith("worker.") and any(
                    p.endswith(".jobs") for p in parts
                ):
                    yield self.finding(
                        ctx, node,
                        "hand-built worker subject f-string; use "
                        "protocol.subjects.direct_subject(worker_id)",
                    )


# ---------------------------------------------------------------------------
# CL006
# ---------------------------------------------------------------------------

_GATED_KWARGS = {"check_vma", "check_rep"}
_JAX_WRAPPERS = {"shard_map", "_shard_map", "jit", "pjit"}


class JaxCompatKwargs(Rule):
    """CL006: version-gated jax kwargs (``check_vma``/``check_rep``) passed
    straight to ``shard_map``/``jit``.  These kwargs get renamed between jax
    minors; a direct pass breaks whole test tiers on version skew (the exact
    bug that took down 9 seed tests on jax 0.4.37).  Route through
    ``cordum_tpu.parallel.compat.shard_map_compat`` which translates or
    drops them per installed version."""

    id = "CL006"
    name = "jax-compat-kwargs"
    description = (
        "version-gated kwargs (check_vma/check_rep) must go through "
        "parallel/compat.py, not straight into shard_map/jit"
    )
    default_allow_paths = ("cordum_tpu/parallel/compat.py",)

    def _callee_name(self, fn: ast.expr) -> str:
        if isinstance(fn, ast.Name):
            return fn.id
        if isinstance(fn, ast.Attribute):
            return fn.attr
        return ""

    def check(self, ctx: LintContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            if self._callee_name(node.func) not in _JAX_WRAPPERS:
                continue
            for kw in node.keywords:
                if kw.arg in _GATED_KWARGS:
                    yield self.finding(
                        ctx, kw.value,
                        f"version-gated kwarg '{kw.arg}' passed directly to "
                        f"{self._callee_name(node.func)}; use "
                        "parallel.compat.shard_map_compat so one module owns "
                        "the version skew",
                    )


# ---------------------------------------------------------------------------
# CL007
# ---------------------------------------------------------------------------

_JSON_CODEC_FNS = {"dumps", "loads", "dump", "load"}


class NoJsonOnHotPath(Rule):
    """CL007: ``json.dumps``/``json.loads`` in scheduler hot-path modules.
    The wire and the stored records are msgpack (ISSUE 6 moved the last
    JSON codecs off the jobstore hot path — a measurable slice of the 1×1
    regression); a JSON call creeping back in silently re-taxes every job.
    Contract JSON (worker env vars) and legacy-read fallbacks live in
    ``infra/codec.py``, which is the one place allowed to import json."""

    id = "CL007"
    name = "no-json-on-hot-path"
    description = (
        "json.dumps/json.loads forbidden in hot-path modules "
        "(infra/jobstore.py, infra/kv.py, infra/statebus.py, "
        "scheduler/engine.py); use infra/codec.py pack_record/unpack_record "
        "or its env-contract helpers"
    )

    # the rule fires ONLY in these modules (inverse of allow_paths)
    default_hot_paths = (
        "cordum_tpu/infra/jobstore.py",
        "cordum_tpu/infra/kv.py",
        "cordum_tpu/infra/statebus.py",
        "cordum_tpu/controlplane/scheduler/engine.py",
    )

    def check(self, ctx: LintContext) -> Iterator[Finding]:
        hot = tuple(self.options.get("hot_paths", self.default_hot_paths))
        if ctx.rel_path not in hot:
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if (
                isinstance(fn, ast.Attribute)
                and fn.attr in _JSON_CODEC_FNS
                and isinstance(fn.value, ast.Name)
                and fn.value.id == "json"
            ):
                yield self.finding(
                    ctx, node,
                    f"json.{fn.attr} on the scheduler hot path; use the "
                    "msgpack codec (infra/codec.py pack_record/unpack_record) "
                    "or, for env-contract JSON, its dumps_env_json/"
                    "loads_env_json helpers",
                )


from .program_rules import PROGRAM_RULES  # noqa: E402 - registry lives here

RULES: tuple[type[Rule], ...] = (
    NoWallClockDeadline,
    NoSilentSwallow,
    NoBlockingInAsync,
    StateTransitionDiscipline,
    SubjectLiterals,
    JaxCompatKwargs,
    NoJsonOnHotPath,
) + PROGRAM_RULES
