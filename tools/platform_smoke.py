#!/usr/bin/env python
"""Platform smoke: the end-to-end acceptance flow against the REAL
multi-process stack (reference ``tools/scripts/platform_smoke.sh`` +
``demo_guardrails.sh``).

Spawns statebus, safety kernel, scheduler, workflow engine, gateway, and a
TPU worker as separate OS processes, then over plain HTTP:

  1. workflow create → run → succeeded (hello echo through the worker)
  2. install demo-guardrails pack (admin)
  3. destructive job → DENIED (+ DLQ entry + remediation available)
  4. full-slice (chips:8) job → APPROVAL_REQUIRED → approve → dispatched
  5. flight recorder: traced job → span waterfall (≥5 spans, ≥4 services),
     cordum_stage_seconds in /metrics, `cordum trace` CLI render
  6. approval-only workflow → approve step → run succeeded
  7. micro-batching: bulk fan-out of ≥32 embed jobs through
     POST /api/v1/jobs:batch coalesces on the worker — at least one flushed
     batch of size ≥8, asserted via the batch span attributes
  8. fleet telemetry: /api/v1/fleet health beacons for every process,
     fleet counters == beacon sums, SLO burn rate, `cordumctl top`
  9. capacity observatory: /api/v1/capacity has a fresh non-zero row for
     every op the run executed, the fleet exposition carries an e2e
     exemplar resolving to a stored trace, and `cordumctl capacity` +
     `cordum traces blame` render
 10. ragged serving: llm.generate sessions with different prompt lengths
     decode through the worker's single ragged mixed prefill+decode entry
     point — `cordum_serving_compile_total{entry="ragged"}` reports
     exactly 1 compiled program, and the capacity matrix's llm.generate
     row carries the warmup compile in its compile split so the
     steady-state tokens/s excludes it
 11. serving drain/failover: a second worker joins, live sessions are
     submitted to the first, and POST /workers/smoke-w1/drain drains it —
     every session completes SUCCEEDED with its full token count (zero
     CANCELLED/FAILED), at least one finishes on the peer (live migration
     or requeue failover), the drained worker beacons draining and exits,
     and the fleet keeps serving afterwards
 12. agentic workflow serving: a 3-turn agent loop over one session —
     each turn a generate → context.update/context.window → generate DAG
     with `cordum.session_key` on the run — keeps every llm.generate of
     the session on ONE worker (scheduler affinity hits observed in the
     fleet exposition), runs its context embeds as real pool jobs, rides
     the INTERACTIVE SLO class, and renders each run as one ≥3-stage
     trace under the run root span; `cordumctl runs` renders the table
 13. prefix cache + session tiering: two llm.generate sessions sharing a
     long system prompt — the second admission maps the cached full pages
     (prefix-hit + skipped-token counters move, outputs identical to the
     first session's); then an idle conversation hibernates to the
     host-RAM cold arena (WORKER_SERVING_HIBERNATE_AFTER=2 on smoke-w2)
     and its next turn restores the cold pages (hibernated/restored
     counters + the restore-pause histogram move) with the full token
     count served exactly once

Exit 0 = PASS.  Usage: python tools/platform_smoke.py [--keep]
"""
from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import tempfile
import time

import httpx

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
STATEBUS_PORT = int(os.environ.get("SMOKE_STATEBUS_PORT", "7421"))
KERNEL_PORT = int(os.environ.get("SMOKE_KERNEL_PORT", "7431"))
GATEWAY_PORT = int(os.environ.get("SMOKE_GATEWAY_PORT", "8082"))
API = f"http://127.0.0.1:{GATEWAY_PORT}"
H_USER = {"X-Api-Key": "smoke-key"}
# X-Principal-Role covers dev open mode (no keys configured); with keys the
# admin key itself grants the role and the header cannot escalate others
H_ADMIN = {"X-Api-Key": "smoke-admin", "X-Principal-Id": "smoke-admin",
           "X-Principal-Role": "admin"}


def log(msg: str) -> None:
    print(f"[smoke] {msg}", flush=True)


def spawn_stack(logdir: str) -> list[subprocess.Popen]:
    base_env = dict(os.environ)
    base_env.update({
        # sharded control plane: 2 statebus keyspace partitions (one process,
        # consecutive ports) × 2 scheduler shards — the ISSUE 5 smoke topology
        "CORDUM_STATEBUS_URL": (
            f"statebus://127.0.0.1:{STATEBUS_PORT},"
            f"statebus://127.0.0.1:{STATEBUS_PORT + 1}"
        ),
        "CORDUM_SCHEDULER_SHARDS": "2",
        "PYTHONPATH": REPO + os.pathsep + base_env.get("PYTHONPATH", ""),
        "CORDUM_FORCE_CPU": "1",
        "JAX_PLATFORMS": "cpu",
        # hermetic placement: don't let the harness's own CPU burn flip
        # workers to overloaded (the smoke asserts exact worker identities)
        "CORDUM_HOST_LOAD": "0",
    })
    sched_env = {
        "SAFETY_KERNEL_ADDR": f"http://127.0.0.1:{KERNEL_PORT}",
        "POOL_CONFIG_PATH": os.path.join(logdir, "pools.yaml"),
        "TIMEOUT_CONFIG_PATH": os.path.join(logdir, "timeouts.yaml"),
        "SCHEDULER_SHARD_COUNT": "2",
    }
    services = [
        ("statebus", "cordum_tpu.cmd.statebus",
         {"STATEBUS_PORT": str(STATEBUS_PORT),
          "STATEBUS_PARTITIONS": "2",
          "STATEBUS_AOF": os.path.join(logdir, "state.aof")}),
        ("kernel", "cordum_tpu.cmd.safety_kernel",
         {"SAFETY_KERNEL_PORT": str(KERNEL_PORT),
          "SAFETY_POLICY_PATH": os.path.join(logdir, "safety.yaml")}),
        ("scheduler-0", "cordum_tpu.cmd.scheduler",
         {**sched_env, "SCHEDULER_SHARD_INDEX": "0"}),
        ("scheduler-1", "cordum_tpu.cmd.scheduler",
         {**sched_env, "SCHEDULER_SHARD_INDEX": "1"}),
        ("wfengine", "cordum_tpu.cmd.workflow_engine", {}),
        ("gateway", "cordum_tpu.cmd.gateway",
         {"GATEWAY_HTTP_ADDR": f"127.0.0.1:{GATEWAY_PORT}",
          "CORDUM_API_KEYS": "smoke-key",
          "CORDUM_ADMIN_KEYS": "smoke-admin",
          # the gateway reads the slo: stanza for the fleet SLO tracker
          "POOL_CONFIG_PATH": os.path.join(logdir, "pools.yaml"),
          "SAFETY_POLICY_PATH": os.path.join(logdir, "safety.yaml")}),
        ("worker", "cordum_tpu.cmd.worker",
         {"WORKER_ID": "smoke-w1", "WORKER_POOL": "tpu",
          "WORKER_TOPICS": "job.tpu.>,job.default,job.hello-pack.echo",
          "WORKER_CAPABILITIES": "tpu,echo",
          "WORKER_HEARTBEAT_INTERVAL": "1",
          # wide micro-batch window: the smoke fan-out arrives spread over
          # the dispatch pipeline's per-job latency, and step 7 asserts a
          # flushed batch of >= 8 (docs/BATCHING.md tuning knobs)
          "WORKER_MAX_BATCH_SIZE": "32",
          "WORKER_BATCH_WAIT_MS": "900"}),
    ]
    # config files used by scheduler + kernel
    with open(os.path.join(logdir, "pools.yaml"), "w") as f:
        f.write(
            "topics:\n  job.default: tpu\n  job.hello-pack.echo: tpu\n  job.tpu.>: tpu\n"
            "pools:\n  tpu:\n    requires: []\n"
            # SLO objective for the fleet telemetry step: every smoke job
            # submits at the default BATCH class
            "slo:\n  batch:\n    job_class: BATCH\n    latency_ms: 5000\n"
            "    latency_target: 0.95\n"
        )
    with open(os.path.join(logdir, "timeouts.yaml"), "w") as f:
        f.write("reconciler:\n  dispatch_timeout_seconds: 60\n"
                "  running_timeout_seconds: 120\n  scan_interval_seconds: 2\n"
                "  pending_replay_seconds: 4\n")
    with open(os.path.join(logdir, "safety.yaml"), "w") as f:
        f.write("default_tenant: default\ntenants:\n  default:\n"
                "    allow_topics: [\"job.*\", \"job.>\"]\nrules: []\n")
    procs = []
    for name, module, extra in services:
        env = dict(base_env)
        env.update(extra)
        logf = open(os.path.join(logdir, f"{name}.log"), "ab")
        p = subprocess.Popen([sys.executable, "-m", module], env=env,
                             stdout=logf, stderr=logf, cwd=REPO)
        procs.append(p)
        log(f"started {name} (pid {p.pid})")
        if name == "statebus":
            time.sleep(0.8)
    return procs


def wait_http(url: str, timeout_s: float = 60.0) -> None:
    t0 = time.time()
    while time.time() - t0 < timeout_s:
        try:
            r = httpx.get(url, timeout=2.0)
            if r.status_code < 500:
                return
        except Exception:
            pass
        time.sleep(0.5)
    raise RuntimeError(f"timed out waiting for {url}")


def wait_job(c: httpx.Client, job_id: str, want: str, timeout_s: float = 60.0) -> dict:
    t0 = time.time()
    doc = {}
    while time.time() - t0 < timeout_s:
        # a transient gateway stall (1-core host: migration/compile churn
        # starves the event loop) must not kill the whole smoke — the
        # deadline above still bounds the wait
        try:
            doc = c.get(f"/api/v1/jobs/{job_id}?result=true").json()
        except httpx.TransportError:
            time.sleep(1.0)
            continue
        if doc.get("state") == want:
            return doc
        if doc.get("state") in ("FAILED", "DENIED", "TIMEOUT", "CANCELLED") and doc.get("state") != want:
            raise RuntimeError(f"job {job_id} reached {doc.get('state')}, wanted {want}: {doc}")
        time.sleep(0.4)
    raise RuntimeError(f"job {job_id} stuck (last: {doc.get('state')}), wanted {want}")


def wait_run(c: httpx.Client, run_id: str, want: str, timeout_s: float = 90.0) -> dict:
    t0 = time.time()
    doc = {}
    while time.time() - t0 < timeout_s:
        try:
            doc = c.get(f"/api/v1/runs/{run_id}").json()
        except httpx.TransportError:  # transient gateway stall; see wait_job
            time.sleep(1.0)
            continue
        if doc.get("status") == want:
            return doc
        if doc.get("status") in ("FAILED", "CANCELLED") and doc.get("status") != want:
            raise RuntimeError(f"run {run_id} reached {doc['status']}, wanted {want}: {doc.get('error')}")
        time.sleep(0.4)
    raise RuntimeError(f"run {run_id} stuck at {doc.get('status')}, wanted {want}")


def main() -> int:
    keep = "--keep" in sys.argv
    # SMOKE_BASE / BASE: target an already-running deployment (compose, k8s)
    # instead of spawning the process stack — the deploy-parity mode used by
    # docs/DEPLOY.md. Key overrides: SMOKE_API_KEY / SMOKE_ADMIN_KEY.
    global API
    external = os.environ.get("SMOKE_BASE") or os.environ.get("BASE")
    if external:
        API = external.rstrip("/")
        H_USER["X-Api-Key"] = os.environ.get("SMOKE_API_KEY", H_USER["X-Api-Key"])
        H_ADMIN["X-Api-Key"] = os.environ.get("SMOKE_ADMIN_KEY", H_ADMIN["X-Api-Key"])
        procs, logdir = [], "(external)"
        log(f"targeting external deployment {API}")
    else:
        logdir = tempfile.mkdtemp(prefix="cordum-smoke-")
        log(f"logs: {logdir}")
        procs = spawn_stack(logdir)
    try:
        wait_http(f"{API}/healthz")
        log("gateway is up")
        with httpx.Client(base_url=API, headers=H_USER, timeout=30.0) as c, \
             httpx.Client(base_url=API, headers=H_ADMIN, timeout=30.0) as admin:
            # worker registered?
            want_worker = "smoke-w1" if not external else ""
            t0 = time.time()
            workers = {}
            while time.time() - t0 < 60:
                workers = c.get("/api/v1/workers").json().get("workers", {})
                if (want_worker in workers) if want_worker else workers:
                    break
                time.sleep(0.5)
            if want_worker:
                assert want_worker in workers, f"worker never registered: {workers}"
            else:
                assert workers, "no workers heartbeating in external deployment"
            log("worker registered with heartbeats")

            # 1. hello workflow end-to-end through the real worker
            wf = {"id": "smoke-hello", "name": "hello",
                  "steps": {"echo": {"topic": "job.hello-pack.echo",
                                     "input": {"op": "echo", "message": "hi ${input.name}"}}}}
            r = c.post("/api/v1/workflows", json=wf)
            assert r.status_code == 201, r.text
            r = c.post("/api/v1/workflows/smoke-hello/runs", json={"input": {"name": "smoke"}})
            run_id = r.json()["run_id"]
            doc = wait_run(c, run_id, "SUCCEEDED")
            echoed = doc["context"]["steps"]["echo"]
            assert "hi smoke" in json.dumps(echoed), echoed
            log(f"1. hello workflow SUCCEEDED (run {run_id[:8]})")

            # 2. install demo-guardrails
            sys.path.insert(0, REPO)
            from cordum_tpu.packs import load_pack_dir

            m = load_pack_dir(os.path.join(REPO, "examples/demo-guardrails"))
            doc = {"id": m.id, "version": m.version,
                   "resources": {"workflows": m.workflows, "schemas": m.schemas},
                   "overlays": {"config": m.config_overlays, "policy": m.policy_overlays},
                   "simulations": m.simulations}
            r = admin.post("/api/v1/packs", json=doc)
            assert r.status_code == 201, r.text
            log("2. demo-guardrails pack installed (simulations passed)")

            # 3. destructive job denied (kernel hot-reloads fragments ≤2s)
            deadline = time.time() + 30
            while True:
                r = c.post("/api/v1/jobs", json={
                    "topic": "job.default", "payload": {"op": "echo"},
                    "metadata": {"risk_tags": ["destructive"]}})
                jid = r.json()["job_id"]
                time.sleep(1.0)
                state = c.get(f"/api/v1/jobs/{jid}").json().get("state")
                if state == "DENIED":
                    break
                if time.time() > deadline:
                    raise RuntimeError(f"destructive job not denied (state={state})")
                time.sleep(1.0)
            dlq = c.get("/api/v1/dlq").json()
            assert any(e["job_id"] == jid for e in dlq["entries"]), dlq
            log("3. destructive job DENIED + dead-lettered")

            # 4. full-slice job → approval → approve → dispatched
            r = c.post("/api/v1/jobs", json={
                "topic": "job.tpu.ops", "payload": {"op": "echo"},
                "metadata": {"capability": "tpu", "requires": ["tpu", "chips:8"]}})
            jid = r.json()["job_id"]
            t0 = time.time()
            while time.time() - t0 < 30:
                state = c.get(f"/api/v1/jobs/{jid}").json().get("state")
                if state == "APPROVAL_REQUIRED":
                    break
                time.sleep(0.4)
            assert state == "APPROVAL_REQUIRED", state
            approvals = c.get("/api/v1/approvals").json()["approvals"]
            assert any(a["job_id"] == jid for a in approvals)
            r = admin.post(f"/api/v1/approvals/{jid}/approve")
            assert r.status_code == 200, r.text
            doc = wait_job(c, jid, "SUCCEEDED")
            log("4. full-slice job approved and executed "
                f"(worker={doc.get('worker_id')})")

            # 5. flight recorder: an end-to-end job yields a queryable span
            # waterfall across >=4 services, stage histograms hit /metrics,
            # and the CLI renders it
            r = c.post("/api/v1/jobs", json={
                "topic": "job.default", "payload": {"op": "echo", "message": "traced"}})
            jid, trace_id = r.json()["job_id"], r.json()["trace_id"]
            wait_job(c, jid, "SUCCEEDED")
            trace = {}
            t0 = time.time()
            while time.time() - t0 < 30:
                trace = c.get(f"/api/v1/traces/{trace_id}").json()
                if trace.get("span_count", 0) >= 5 and len(trace.get("services") or []) >= 4:
                    break
                time.sleep(0.5)
            assert trace.get("span_count", 0) >= 5, trace
            services = set(trace.get("services") or [])
            assert {"gateway", "scheduler", "safety-kernel", "worker"} <= services, services
            assert trace.get("critical_path"), trace
            metrics_text = httpx.get(f"{API}/metrics", timeout=10.0).text
            stage_counts = [
                ln for ln in metrics_text.splitlines()
                if ln.startswith("cordum_stage_seconds_count") and not ln.rstrip().endswith(" 0")
            ]
            assert stage_counts, "no non-zero cordum_stage_seconds in /metrics"
            # retention caps must not have silently truncated any trace
            # (cordum_spans_dropped_total stays 0 through the whole run)
            dropped = [
                ln for ln in metrics_text.splitlines()
                if ln.startswith("cordum_spans_dropped_total")
                and not ln.rstrip().endswith(" 0") and not ln.rstrip().endswith(" 0.0")
            ]
            assert not dropped, f"spans dropped during smoke: {dropped}"
            cli = subprocess.run(
                [sys.executable, "-m", "cordum_tpu.cli", "trace", trace_id],
                capture_output=True, text=True, timeout=30, cwd=REPO,
                env={**os.environ, "CORDUM_API_URL": API,
                     "CORDUM_API_KEY": H_USER["X-Api-Key"],
                     "PYTHONPATH": REPO + os.pathsep + os.environ.get("PYTHONPATH", "")},
            )
            assert cli.returncode == 0 and f"trace {trace_id}" in cli.stdout, cli.stderr
            log(f"5. trace {trace_id[:8]} has {trace['span_count']} spans over "
                f"{len(services)} services; stage histograms live; CLI waterfall OK")

            # 6. approval workflow (guarded-inference from the pack)
            r = c.post("/api/v1/workflows/guarded-inference/runs",
                       json={"input": {"tokens": [[1, 2, 3]]}})
            run_id = r.json()["run_id"]
            t0 = time.time()
            while time.time() - t0 < 30:
                st = c.get(f"/api/v1/runs/{run_id}").json()["status"]
                if st == "WAITING_APPROVAL":
                    break
                time.sleep(0.4)
            assert st == "WAITING_APPROVAL", st
            r = admin.post(f"/api/v1/runs/{run_id}/steps/gate/approve", json={"approve": True})
            assert r.status_code == 200, r.text
            wait_run(c, run_id, "SUCCEEDED")
            log("6. guarded-inference run approved → SUCCEEDED")

            # 7. micro-batching: a bulk fan-out of 32 single-text embed jobs
            # must coalesce on the worker — at least one flushed batch of
            # size >= 8, proven by the batch attributes the flush writes
            # onto the execute spans
            n_fan = 32
            r = c.post("/api/v1/jobs:batch", json={"jobs": [
                {"topic": "job.tpu.ops",
                 "payload": {"op": "embed",
                             "texts": [f"microbatch smoke document {i}"]}}
                for i in range(n_fan)]})
            assert r.status_code == 202, r.text
            docs = r.json()["jobs"]
            assert len(docs) == n_fan and all(d.get("job_id") for d in docs), docs
            for d in docs:
                wait_job(c, d["job_id"], "SUCCEEDED")
            best = 0
            t0 = time.time()
            while time.time() - t0 < 30 and best < 8:
                best = 0
                for d in docs:
                    trace = c.get(f"/api/v1/traces/{d['trace_id']}").json()
                    for sp in trace.get("spans") or []:
                        size = (sp.get("attrs") or {}).get("batch_size", "")
                        if size.isdigit():
                            best = max(best, int(size))
                if best < 8:
                    time.sleep(0.5)
            assert best >= 8, f"largest flushed batch was {best}, wanted >= 8"
            log(f"7. bulk fan-out of {n_fan} embed jobs coalesced "
                f"(largest flushed batch {best})")

            # 8. fleet telemetry plane: /api/v1/fleet must show every
            # process's health beacon (gateway, 2 scheduler shards, statebus
            # partitions, worker, kernel, wf-engine), a fleet-wide scheduled
            # counter matching the per-shard beacon sum, a non-zero job rate
            # over the run, and an SLO burn rate for the configured class —
            # and `cordumctl top` must render it
            want_services = {"gateway", "scheduler", "statebus", "worker"}
            fleet = {}
            t0 = time.time()
            while time.time() - t0 < 45:
                fleet = c.get("/api/v1/fleet").json()
                healthy = {s["service"] for s in fleet.get("services", [])
                           if s.get("healthy")}
                if (want_services <= healthy
                        and fleet.get("healthy_services", 0) >= 4
                        and fleet["fleet"].get("jobs_dispatched_total", 0) > 0):
                    break
                time.sleep(1.0)
            healthy = {s["service"] for s in fleet["services"] if s["healthy"]}
            assert want_services <= healthy, f"missing beacons: {healthy}"
            assert fleet["healthy_services"] >= 4, fleet["counts"]
            shards = [s for s in fleet["services"]
                      if s["service"] == "scheduler" and s["healthy"]]
            assert len(shards) == 2, f"expected 2 scheduler shards: {shards}"
            assert {s.get("shard_index") for s in shards} == {0, 1}, shards
            parts = [s for s in fleet["services"]
                     if s["service"] == "statebus" and s["healthy"]]
            assert {p.get("partition") for p in parts} == {0, 1}, parts
            # fleet-wide scheduled counter == sum of the per-shard beacons
            beacon_sum = sum(s.get("jobs_scheduled", 0) for s in shards)
            assert fleet["fleet"]["jobs_dispatched_total"] == beacon_sum > 0, (
                fleet["fleet"], shards)
            # every earlier step ran jobs: the run-window rate is non-zero
            assert fleet["fleet"]["completed_5m"] > 0, fleet["fleet"]
            # the SLO tracker reports a burn rate for the configured class
            slo = {s["name"]: s for s in fleet.get("slo", [])}
            assert "batch" in slo, fleet.get("slo")
            w5 = slo["batch"]["windows"]["5m"]
            assert w5["total"] > 0 and w5["burn_rate"] >= 0.0, w5
            assert slo["batch"]["state"] in ("ok", "warn", "page"), slo
            assert fleet["fleet"]["spans_dropped_total"] == 0, fleet["fleet"]
            top = subprocess.run(
                [sys.executable, "-m", "cordum_tpu.cli", "top", "--once"],
                capture_output=True, text=True, timeout=30, cwd=REPO,
                env={**os.environ, "CORDUM_API_URL": API,
                     "CORDUM_API_KEY": H_USER["X-Api-Key"],
                     "PYTHONPATH": REPO + os.pathsep + os.environ.get("PYTHONPATH", "")},
            )
            assert top.returncode == 0, top.stderr
            for needle in ("scheduler", "statebus", "worker", "slo batch"):
                assert needle in top.stdout, (needle, top.stdout)
            log(f"8. fleet telemetry: {fleet['healthy_services']} healthy beacons "
                f"({sorted(healthy)}), fleet scheduled={beacon_sum}, slo "
                f"burn5m={w5['burn_rate']} ({slo['batch']['state']}); "
                "cordumctl top renders")

            # 9. capacity observatory: GET /api/v1/capacity must report a
            # fresh non-zero throughput row for every op this run executed
            # (echo via the workflow/approval jobs, embed via the batch
            # fan-out), the fleet exposition must carry the matrix gauges
            # plus an e2e exemplar that resolves to a stored trace, and the
            # critical-path blame surfaces must render
            import re

            want_ops = {"echo", "embed"}
            cap, fresh_ops = {}, set()
            t0 = time.time()
            while time.time() - t0 < 45:
                cap = c.get("/api/v1/capacity").json()
                fresh_ops = {r["op"] for r in cap.get("matrix", [])
                             if not r["stale"] and r["items_per_s"] > 0}
                if want_ops <= fresh_ops:
                    break
                time.sleep(1.0)
            assert want_ops <= fresh_ops, (
                f"capacity matrix missing fresh ops: {fresh_ops} from "
                f"{cap.get('matrix')}")
            ages = [r["age_s"] for r in cap["matrix"] if r["op"] in want_ops]
            assert ages and min(ages) < 30, f"stale capacity rows: {ages}"
            assert cap["workers"], cap
            assert all(cap["ops"].get(op, 0) > 0 for op in want_ops), cap["ops"]
            fleet_text = httpx.get(f"{API}/metrics?scope=fleet",
                                   timeout=10.0).text
            assert "cordum_capacity_items_per_sec" in fleet_text
            # the acceptance link: an e2e histogram exemplar's trace id must
            # resolve to a stored trace with spans
            m = re.search(
                r'cordum_job_e2e_seconds_bucket\{[^}]*\} [0-9.]+ '
                r'# \{trace_id="([^"]+)"\}', fleet_text)
            assert m, "no exemplar on cordum_job_e2e_seconds in fleet scope"
            ex_trace = c.get(f"/api/v1/traces/{m.group(1)}").json()
            assert ex_trace.get("span_count", 0) >= 1, ex_trace
            blame = c.get("/api/v1/traces/analysis").json()
            assert blame["traces"] > 0, blame
            assert "execute" in blame["stages"], blame["stages"]
            share_sum = sum(s["blame_share"] for s in blame["stages"].values())
            assert 0.98 <= share_sum <= 1.02, (share_sum, blame["stages"])
            for cmd, needles in (
                (["capacity"], ("echo", "embed", "items/s")),
                (["traces", "blame", "--last", "50"],
                 ("critical-path blame", "execute")),
            ):
                cp = subprocess.run(
                    [sys.executable, "-m", "cordum_tpu.cli", *cmd],
                    capture_output=True, text=True, timeout=30, cwd=REPO,
                    env={**os.environ, "CORDUM_API_URL": API,
                         "CORDUM_API_KEY": H_USER["X-Api-Key"],
                         "PYTHONPATH": REPO + os.pathsep
                         + os.environ.get("PYTHONPATH", "")},
                )
                assert cp.returncode == 0, (cmd, cp.stderr)
                for needle in needles:
                    assert needle in cp.stdout, (cmd, needle, cp.stdout)
            log(f"9. capacity observatory: fresh rows for {sorted(fresh_ops)}, "
                f"e2e exemplar {m.group(1)[:8]} resolves "
                f"({ex_trace['span_count']} spans), blame shares sum to "
                f"{share_sum:.3f}; cordumctl capacity + traces blame render")

            # 10. ragged serving: mixed-length llm.generate sessions through
            # the single ragged entry point — one compiled XLA program for
            # the whole mix (no prompt-length/batch buckets), and the
            # capacity matrix's steady-state decode rate excludes the
            # warmup compile via the compile split
            gen_docs = []
            for i, plen in enumerate((3, 7, 12)):  # different "buckets"
                r = c.post("/api/v1/jobs", json={
                    "topic": "job.tpu.generate",
                    "payload": {"op": "llm.generate",
                                "tokens": list(range(1, plen + 1)),
                                "max_new_tokens": 8,
                                "session_id": f"smoke-conv-{i}"}})
                assert r.status_code == 202, r.text
                gen_docs.append(r.json())
            results = [wait_job(c, d["job_id"], "SUCCEEDED") for d in gen_docs]
            for d in results:
                assert len(d["result"]["tokens"]) == 8, d["result"]
            # the whole mixed run compiled exactly ONE serving program
            compile_lines = {}
            srv_row = {}
            t0 = time.time()
            while time.time() - t0 < 45:
                fleet_text = httpx.get(f"{API}/metrics?scope=fleet",
                                       timeout=10.0).text
                compile_lines = {
                    ln.rsplit(" ", 1)[0]: float(ln.rsplit(" ", 1)[1])
                    for ln in fleet_text.splitlines()
                    if ln.startswith("cordum_serving_compile_total{")
                }
                cap = c.get("/api/v1/capacity").json()
                srv_row = next((r for r in cap.get("matrix", [])
                                if r["op"] == "llm.generate"), {})
                if compile_lines and srv_row.get("tokens_per_s", 0) > 0:
                    break
                time.sleep(1.0)
            ragged = [v for k, v in compile_lines.items()
                      if 'entry="ragged"' in k]
            assert ragged == [1.0], (
                f"expected exactly one ragged compile: {compile_lines}")
            # the warmup compile rides the capacity compile split of
            # whichever phase row the first step served — the mixed step's
            # device time now splits into llm.prefill + llm.generate rows
            # (docs/SERVING.md §Disaggregation) — and the steady-state rate
            # the matrix reports excludes it either way
            pre_row = next((r for r in cap.get("matrix", [])
                            if r["op"] == "llm.prefill"), {})
            assert (srv_row.get("compile_n", 0)
                    + pre_row.get("compile_n", 0)) >= 1, (srv_row, pre_row)
            assert srv_row.get("n", 0) > srv_row.get("compile_n", 0), srv_row
            assert srv_row.get("tokens_per_s", 0) > 0, srv_row
            assert pre_row.get("tokens_per_s", 0) > 0, pre_row
            log(f"10. ragged serving: 3 mixed-length sessions decoded, "
                f"1 compiled program, capacity row steady tokens/s="
                f"{srv_row['tokens_per_s']} (compile_n={srv_row['compile_n']} "
                f"of n={srv_row['n']} excluded)")

            # 11. serving drain/failover: a second worker joins; live
            # sessions pinned to smoke-w1 are drained off it mid-decode —
            # live KV-page migration to the peer, with scheduler requeue
            # (failover) as the fallback for a dispatch that raced the
            # draining beacon.  Zero CANCELLED/FAILED sessions either way.
            if not external:
                w2_env = dict(os.environ)
                w2_env.update({
                    "CORDUM_STATEBUS_URL": (
                        f"statebus://127.0.0.1:{STATEBUS_PORT},"
                        f"statebus://127.0.0.1:{STATEBUS_PORT + 1}"),
                    "CORDUM_SCHEDULER_SHARDS": "2",
                    "PYTHONPATH": REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
                    "CORDUM_FORCE_CPU": "1", "JAX_PLATFORMS": "cpu",
                    # hermetic like the boot-time workers: without this the
                    # only post-drain worker senses the harness's own CPU
                    # burn, reads overloaded (cpu_load>=90), and every
                    # affinity election silently fails onto topic fan-in —
                    # step 12's session-affinity hits become impossible
                    "CORDUM_HOST_LOAD": "0",
                    "WORKER_ID": "smoke-w2", "WORKER_POOL": "tpu",
                    "WORKER_TOPICS": "job.tpu.>,job.default,job.hello-pack.echo",
                    "WORKER_CAPABILITIES": "tpu,echo",
                    "WORKER_HEARTBEAT_INTERVAL": "1",
                    # step 13 rides this worker: idle conversations
                    # hibernate to the host cold arena after 2s
                    # (docs/SERVING.md §Prefix cache and tiering)
                    "WORKER_SERVING_HIBERNATE_AFTER": "2",
                })
                w2_log = open(os.path.join(logdir, "worker2.log"), "ab")
                w2 = subprocess.Popen(
                    [sys.executable, "-m", "cordum_tpu.cmd.worker"],
                    env=w2_env, stdout=w2_log, stderr=w2_log, cwd=REPO)
                procs.append(w2)
                t0 = time.time()
                while time.time() - t0 < 60:
                    if "smoke-w2" in c.get("/api/v1/workers").json().get("workers", {}):
                        break
                    time.sleep(0.5)
                assert "smoke-w2" in c.get("/api/v1/workers").json()["workers"]
                drain_docs = []
                for i in range(3):
                    r = c.post("/api/v1/jobs", json={
                        "topic": "job.tpu.generate",
                        "payload": {"op": "llm.generate",
                                    "tokens": list(range(2, 10)),
                                    "max_new_tokens": 48,
                                    "session_id": f"drain-conv-{i}"},
                        "labels": {"preferred_worker_id": "smoke-w1"}})
                    assert r.status_code == 202, r.text
                    drain_docs.append(r.json())
                # drain while the sessions are in flight
                r = admin.post("/api/v1/workers/smoke-w1/drain",
                               json={"reason": "smoke step 11"})
                assert r.status_code == 202, r.text
                finals = [wait_job(c, d["job_id"], "SUCCEEDED", 90)
                          for d in drain_docs]
                peer_finishes = 0
                for d, doc in zip(drain_docs, finals):
                    assert len(doc["result"]["tokens"]) == 48, doc["result"]
                    events = [e.get("event") for e in
                              c.get(f"/api/v1/jobs/{d['job_id']}?events=true")
                              .json().get("events", [])]
                    assert "cancelled" not in events, (d["job_id"], events)
                    if doc.get("worker_id") == "smoke-w2":
                        peer_finishes += 1
                assert peer_finishes >= 1, (
                    f"no session finished on the peer: {[f.get('worker_id') for f in finals]}")
                # the drained worker beacons draining (then deregisters) and
                # its process exits on its own
                t0 = time.time()
                w1_gone = False
                while time.time() - t0 < 60:
                    ws = c.get("/api/v1/workers").json().get("workers", {})
                    hb = ws.get("smoke-w1")
                    if hb is None or hb.get("draining"):
                        w1_gone = True
                        break
                    time.sleep(0.5)
                assert w1_gone, "smoke-w1 never beaconed draining"
                # the fleet keeps serving: a fresh session completes on w2
                r = c.post("/api/v1/jobs", json={
                    "topic": "job.tpu.generate",
                    "payload": {"op": "llm.generate", "tokens": [3, 1, 4],
                                "max_new_tokens": 8,
                                "session_id": "post-drain-conv"}})
                doc = wait_job(c, r.json()["job_id"], "SUCCEEDED", 60)
                assert doc.get("worker_id") == "smoke-w2", doc.get("worker_id")
                log(f"11. drain/failover: 3 sessions survived the drain "
                    f"({peer_finishes} finished on smoke-w2), zero CANCELLED, "
                    "post-drain traffic serves on the peer")
            else:
                log("11. drain/failover: skipped (external deployment)")

            # 12. agentic workflow serving (docs/WORKFLOWS.md): a 3-turn
            # agent loop on ONE session.  Every run carries the same
            # cordum.session_key, so the engine stamps session_id into each
            # llm.generate payload and the scheduler's affinity cache keeps
            # the whole session on one worker; context.update/window run
            # in-engine with their embeds riding the pool as real embed
            # jobs; the workflow's INTERACTIVE slo_class lands on the run
            # labels; each run renders as one trace under the run root span.
            def _affinity_hits(text: str) -> float:
                return sum(
                    float(ln.rsplit(" ", 1)[1]) for ln in text.splitlines()
                    if ln.startswith("cordum_session_affinity_total{")
                    and 'outcome="hit"' in ln)

            hits_before = _affinity_hits(
                httpx.get(f"{API}/metrics?scope=fleet", timeout=10.0).text)
            wf = {"id": "smoke-agent", "name": "agent loop",
                  "slo_class": "INTERACTIVE",
                  "steps": {
                      "plan": {"topic": "job.tpu.generate",
                               "input": {"op": "llm.generate",
                                         "tokens": [2, 7, 1],
                                         "max_new_tokens": 6}},
                      "remember": {"topic": "job.tpu.context",
                                   "depends_on": ["plan"],
                                   "input": {"op": "context.update",
                                             "user_payload": "${input.goal}",
                                             "model_response":
                                                 "plan ${steps.plan.tokens}",
                                             "chunks": [{
                                                 "file_path": "notes",
                                                 "content": "agent planned "
                                                            "${steps.plan.tokens}"}]}},
                      "window": {"topic": "job.tpu.context",
                                 "depends_on": ["remember"],
                                 "input": {"op": "context.window",
                                           "mode": "RAG",
                                           "query": "${input.goal}"}},
                      "act": {"topic": "job.tpu.generate",
                              "depends_on": ["window"],
                              "input": {"op": "llm.generate",
                                        "tokens": [4, 4, 8],
                                        "max_new_tokens": 6}},
                  }}
            r = c.post("/api/v1/workflows", json=wf)
            assert r.status_code == 201, r.text
            turn_workers = []
            last_run = {}
            for turn in range(3):
                r = c.post("/api/v1/workflows/smoke-agent/runs",
                           json={"input": {"goal": f"agent smoke turn {turn}"},
                                 "labels": {"cordum.session_key": "agent-smoke"}})
                assert r.status_code == 202, r.text
                run_id = r.json()["run_id"]
                last_run = wait_run(c, run_id, "SUCCEEDED")
                steps_ctx = last_run["context"]["steps"]
                # the RAG window saw the memory this (and earlier) turns wrote
                assert steps_ctx["window"]["message_count"] >= 1, steps_ctx["window"]
                assert len(steps_ctx["act"]["tokens"]) == 6, steps_ctx["act"]
                workers = {}
                for sid in ("plan", "act"):
                    jd = c.get(f"/api/v1/jobs/{run_id}:{sid}@1").json()
                    assert jd.get("state") == "SUCCEEDED", jd
                    workers[sid] = jd.get("worker_id", "")
                turn_workers.append(workers)
            assert last_run.get("labels", {}).get("cordum.slo_class") == "INTERACTIVE", \
                last_run.get("labels")
            if not external:
                # every llm.generate of the session stayed on the one live
                # worker — the no-re-prefill contract
                owners = {w for tw in turn_workers for w in tw.values()}
                assert owners == {"smoke-w2"}, f"session hopped workers: {turn_workers}"
                # and the affinity cache produced real hits (6 session jobs
                # over <=2 shards: some shard routed a repeat)
                hits_after, aff_lines = hits_before, []
                t0 = time.time()
                while time.time() - t0 < 30 and hits_after <= hits_before:
                    fleet_text = httpx.get(f"{API}/metrics?scope=fleet",
                                           timeout=10.0).text
                    hits_after = _affinity_hits(fleet_text)
                    aff_lines = [
                        ln for ln in fleet_text.splitlines()
                        if ln.startswith("cordum_session_affinity_total")]
                    if hits_after <= hits_before:
                        time.sleep(1.0)
                # failure triage: no lines at all = the serving placement
                # path never engaged (scheduler's capacity view had no
                # fresh prefill rate — beacon starvation under load);
                # new/miss lines without hit = no shard saw a repeat
                assert hits_after > hits_before, (
                    hits_before, hits_after, aff_lines)
            # one trace per run: the run root span plus >=3 distinct DAG
            # stages parented under it
            trace_id = last_run.get("trace_id", "")
            assert trace_id, last_run
            trace, stages, names = {}, set(), set()
            t0 = time.time()
            while time.time() - t0 < 30:
                trace = c.get(f"/api/v1/traces/{trace_id}").json()
                spans = trace.get("spans") or []
                stages = {(sp.get("attrs") or {}).get("step")
                          for sp in spans} - {None}
                names = {sp.get("name") for sp in spans}
                if len(stages) >= 3 and "workflow-run" in names:
                    break
                time.sleep(0.5)
            assert len(stages) >= 3, (stages, trace.get("span_count"))
            assert "workflow-run" in names, names
            runs_out = subprocess.run(
                [sys.executable, "-m", "cordum_tpu.cli", "runs",
                 "--workflow-id", "smoke-agent"],
                capture_output=True, text=True, timeout=30, cwd=REPO,
                env={**os.environ, "CORDUM_API_URL": API,
                     "CORDUM_API_KEY": H_USER["X-Api-Key"],
                     "PYTHONPATH": REPO + os.pathsep
                     + os.environ.get("PYTHONPATH", "")},
            )
            assert runs_out.returncode == 0, runs_out.stderr
            assert "smoke-agent" in runs_out.stdout, runs_out.stdout
            assert "INTERACTIVE" in runs_out.stdout, runs_out.stdout
            log(f"12. agent loop: 3 turns on one session, workers={turn_workers[-1]}, "
                f"window={last_run['context']['steps']['window']['message_count']} msgs, "
                f"trace stages={sorted(stages)}; cordumctl runs renders")

            # 13. prefix cache + session tiering (docs/SERVING.md §Prefix
            # cache and tiering): two sessions share a long system prompt —
            # the second admission maps the cached full pages and skips
            # their prefill (hit + skipped-token counters move, outputs
            # stay identical: sharing is a placement change, not a math
            # change).  Then an idle conversation hibernates to the
            # host-RAM cold arena (smoke-w2 runs with
            # WORKER_SERVING_HIBERNATE_AFTER=2) and its next turn restores
            # the cold pages — hibernated/restored counters and the
            # restore-pause histogram move, and the terminal result carries
            # the full token count exactly once.
            if not external:
                def _ctr(text: str, name: str, match: str = "") -> float:
                    return sum(
                        float(ln.rsplit(" ", 1)[1]) for ln in text.splitlines()
                        if ln.startswith(name) and match in ln)

                def _fleet() -> str:
                    return httpx.get(f"{API}/metrics?scope=fleet",
                                     timeout=10.0).text

                before = _fleet()
                hits0 = _ctr(before, "cordum_serving_prefix_total{",
                             'outcome="hit"')
                skip0 = _ctr(before, "cordum_serving_prefix_tokens_total")
                hib0 = _ctr(before, "cordum_serving_hibernate_total{",
                            'event="hibernated"')
                res0 = _ctr(before, "cordum_serving_hibernate_total{",
                            'event="restored"')
                pause0 = _ctr(before,
                              "cordum_serving_hibernate_pause_seconds_count")
                # 40 shared tokens = 2 cacheable full 16-slot pages
                system = [((7 * i) % 250) + 2 for i in range(40)]
                docs = []
                for sid in ("pfx-a", "pfx-b"):
                    r = c.post("/api/v1/jobs", json={
                        "topic": "job.tpu.generate",
                        "payload": {"op": "llm.generate", "tokens": system,
                                    "max_new_tokens": 8, "session_id": sid}})
                    assert r.status_code == 202, r.text
                    docs.append(wait_job(c, r.json()["job_id"],
                                         "SUCCEEDED", 60))
                assert docs[0]["result"]["tokens"] == docs[1]["result"]["tokens"], \
                    "prefix sharing changed the generated tokens"
                # the fleet scope is fed by 2s worker beacons — poll until
                # the hit/skipped counters propagate instead of racing them
                after, t0 = _fleet(), time.time()
                while time.time() - t0 < 20 and (
                        _ctr(after, "cordum_serving_prefix_total{",
                             'outcome="hit"') < hits0 + 1
                        or _ctr(after, "cordum_serving_prefix_tokens_total")
                        < skip0 + 32):
                    time.sleep(1.0)
                    after = _fleet()
                assert _ctr(after, "cordum_serving_prefix_total{",
                            'outcome="hit"') >= hits0 + 1, "no prefix hit"
                skipped = _ctr(after,
                               "cordum_serving_prefix_tokens_total") - skip0
                assert skipped >= 32, (
                    f"second session's prefill skipped only {skipped} of the "
                    "32 shared full-page tokens")
                # hibernate: one turn, go idle past the 2s threshold, then
                # the next turn restores the conversation's cold pages
                hib_p = [((13 * i) % 250) + 3 for i in range(20)]
                r = c.post("/api/v1/jobs", json={
                    "topic": "job.tpu.generate",
                    "payload": {"op": "llm.generate", "tokens": hib_p,
                                "max_new_tokens": 8,
                                "session_id": "hib-conv"}})
                turn1 = wait_job(c, r.json()["job_id"], "SUCCEEDED", 60)
                # other idle conversations (pfx-a/b, the agent loop) also
                # hibernate, so a bare counter bump can't prove hib-conv
                # went cold — and the fleet scope sums BOTH workers'
                # resident gauges (drained smoke-w1 never sweeps), so
                # "zero warm anywhere" is unreachable.  Instead wait for
                # the sweeps to QUIESCE: hib-conv's lone full page has
                # refcount 1 after its clean retire, so once the
                # hibernated counter has moved and then stayed flat for
                # 5 consecutive 1s polls (>> the 2s idle threshold +
                # 0.5s sweep interval), every demotable page — hib-conv's
                # included — is in the cold arena
                t0 = time.time()
                hibernated, cold, stable, prev = hib0, 0.0, 0, -1.0
                while time.time() - t0 < 60 and stable < 5:
                    time.sleep(1.0)
                    txt = _fleet()
                    hibernated = _ctr(txt, "cordum_serving_hibernate_total{",
                                      'event="hibernated"')
                    cold = _ctr(txt, "cordum_serving_resident_sessions{",
                                'tier="cold"')
                    stable = (stable + 1
                              if hibernated > hib0 and hibernated == prev
                              else 0)
                    prev = hibernated
                assert hibernated > hib0, "idle conversation never hibernated"
                assert stable >= 5, "hibernate sweep never quiesced"
                assert cold >= 1, f"no conversation went cold: cold={cold}"
                turn2_prompt = hib_p + turn1["result"]["tokens"] + [5]
                r = c.post("/api/v1/jobs", json={
                    "topic": "job.tpu.generate",
                    "payload": {"op": "llm.generate", "tokens": turn2_prompt,
                                "max_new_tokens": 8,
                                "session_id": "hib-conv"}})
                turn2 = wait_job(c, r.json()["job_id"], "SUCCEEDED", 60)
                # exactly-once: the terminal result is the full generation
                assert len(turn2["result"]["tokens"]) == 8, turn2["result"]
                final, t0 = _fleet(), time.time()
                while time.time() - t0 < 20 and (
                        _ctr(final, "cordum_serving_hibernate_total{",
                             'event="restored"') <= res0
                        or _ctr(final,
                                "cordum_serving_hibernate_pause_seconds_count")
                        <= pause0):
                    time.sleep(1.0)
                    final = _fleet()
                assert _ctr(final, "cordum_serving_hibernate_total{",
                            'event="restored"') > res0, "no cold-page restore"
                assert _ctr(final,
                            "cordum_serving_hibernate_pause_seconds_count") \
                    > pause0, "restore pause never observed"
                log(f"13. prefix+tiering: shared-prefix hit skipped "
                    f"{skipped:.0f} prompt tokens (outputs identical), "
                    f"idle conversation hibernated and restored on turn 2 "
                    f"({len(turn2['result']['tokens'])} tokens exactly once)")
            else:
                log("13. prefix+tiering: skipped (external deployment)")

            # 14. speculative decoding (docs/SERVING.md §Speculative
            # decoding): a templated (motif-heavy) llm.generate session on
            # the live stack engages the prompt-lookup drafter — non-zero
            # drafts verified and ACCEPTED through the ragged step — while
            # a control worker started with WORKER_SERVING_SPECULATIVE=0
            # generates the identical token sequence for the same prompt
            # (speculation is a schedule change, not a math change).  The
            # accept EWMA rides only the spec worker's occupancy beacon,
            # and no worker ever compiled a second ragged program: draft
            # verification rows are prefill-shaped, so they reuse the one
            # static-shape serving executable.
            if not external:
                def _spec_fleet() -> str:
                    return httpx.get(f"{API}/metrics?scope=fleet",
                                     timeout=10.0).text

                def _spec_ctr(text: str, name: str) -> float:
                    return sum(
                        float(ln.rsplit(" ", 1)[1]) for ln in text.splitlines()
                        if ln.startswith(name))

                before = _spec_fleet()
                drafted0 = _spec_ctr(before,
                                     "cordum_serving_spec_drafted_total")
                acc0 = _spec_ctr(before,
                                 "cordum_serving_spec_accepted_total")

                def _spec_ragged(text: str) -> float:
                    return sum(
                        float(ln.rsplit(" ", 1)[1]) for ln in text.splitlines()
                        if ln.startswith("cordum_serving_compile_total{")
                        and 'entry="ragged"' in ln)

                ragged0 = _spec_ragged(before)
                # the spec-disabled control worker: same model, same pool,
                # speculation forced off
                w3_env = dict(os.environ)
                w3_env.update({
                    "CORDUM_STATEBUS_URL": (
                        f"statebus://127.0.0.1:{STATEBUS_PORT},"
                        f"statebus://127.0.0.1:{STATEBUS_PORT + 1}"),
                    "CORDUM_SCHEDULER_SHARDS": "2",
                    "PYTHONPATH": REPO + os.pathsep
                    + os.environ.get("PYTHONPATH", ""),
                    "CORDUM_FORCE_CPU": "1", "JAX_PLATFORMS": "cpu",
                    "CORDUM_HOST_LOAD": "0",
                    "WORKER_ID": "smoke-w3", "WORKER_POOL": "tpu",
                    "WORKER_TOPICS": "job.tpu.>,job.default",
                    "WORKER_CAPABILITIES": "tpu",
                    "WORKER_HEARTBEAT_INTERVAL": "1",
                    "WORKER_SERVING_SPECULATIVE": "0",
                })
                w3_log = open(os.path.join(logdir, "worker3.log"), "ab")
                w3 = subprocess.Popen(
                    [sys.executable, "-m", "cordum_tpu.cmd.worker"],
                    env=w3_env, stdout=w3_log, stderr=w3_log, cwd=REPO)
                procs.append(w3)
                t0 = time.time()
                while time.time() - t0 < 60:
                    if "smoke-w3" in c.get("/api/v1/workers").json().get(
                            "workers", {}):
                        break
                    time.sleep(0.5)
                assert "smoke-w3" in c.get("/api/v1/workers").json()["workers"]
                # templated prompt: a repeated motif the n-gram drafter can
                # look up (agent-loop prompts share this shape)
                motif = [5, 9, 14, 23, 7, 11, 3, 19]
                tpl = motif * 4 + [2]

                def _spec_gen(sid: str, wid: str) -> dict:
                    r = c.post("/api/v1/jobs", json={
                        "topic": "job.tpu.generate",
                        "payload": {"op": "llm.generate",
                                    "tokens": list(tpl),
                                    "max_new_tokens": 48,
                                    "session_id": sid},
                        "labels": {"preferred_worker_id": wid}})
                    assert r.status_code == 202, r.text
                    return wait_job(c, r.json()["job_id"], "SUCCEEDED", 90)

                spec_doc = _spec_gen("spec-conv", "smoke-w2")
                ctrl_doc = _spec_gen("spec-ctrl-conv", "smoke-w3")
                assert spec_doc.get("worker_id") == "smoke-w2", spec_doc
                assert ctrl_doc.get("worker_id") == "smoke-w3", ctrl_doc
                assert len(spec_doc["result"]["tokens"]) == 48, spec_doc
                assert spec_doc["result"]["tokens"] == \
                    ctrl_doc["result"]["tokens"], (
                        "speculation changed the generated tokens")
                # the spec worker verified and accepted real drafts
                after, t0 = _spec_fleet(), time.time()
                while time.time() - t0 < 30 and (
                        _spec_ctr(after, "cordum_serving_spec_accepted_total")
                        <= acc0):
                    time.sleep(1.0)
                    after = _spec_fleet()
                drafted = _spec_ctr(
                    after, "cordum_serving_spec_drafted_total") - drafted0
                accepted = _spec_ctr(
                    after, "cordum_serving_spec_accepted_total") - acc0
                assert drafted > 0, "no tokens were ever drafted"
                assert accepted > 0, "no drafted token was ever accepted"
                # the acceptance EWMA beacons from the spec worker only;
                # the control worker's occupancy never carries the key
                occ2, occ3, t0 = {}, {}, time.time()
                while time.time() - t0 < 30:
                    cap_workers = c.get("/api/v1/capacity").json().get(
                        "workers", {})
                    occ2 = (cap_workers.get("smoke-w2") or {}).get(
                        "occupancy") or {}
                    occ3 = (cap_workers.get("smoke-w3") or {}).get(
                        "occupancy") or {}
                    if "spec_accept_rate" in occ2 and occ3:
                        break
                    time.sleep(1.0)
                assert "spec_accept_rate" in occ2, occ2
                assert "spec_accept_rate" not in occ3, occ3
                # draft rows never grew the compile ladder: the fleet
                # counter sums one warmup compile per worker, so the spec
                # session on the already-warm smoke-w2 must add ZERO and
                # the fresh control worker exactly its one warmup
                ragged_added = _spec_ragged(after) - ragged0
                assert ragged_added == 1.0, (
                    f"draft rows recompiled the serving program: "
                    f"{ragged_added} new ragged compiles (expected only "
                    "the control worker's warmup)")
                log(f"14. speculative decoding: templated session accepted "
                    f"{accepted:.0f} of {drafted:.0f} drafted tokens on "
                    f"smoke-w2, tokens identical to the spec-disabled "
                    f"control (smoke-w3), accept EWMA beacons from the spec "
                    f"worker only, zero new ragged compiles on the warm "
                    f"worker")
            else:
                log("14. speculative decoding: skipped (external deployment)")

            # 15. sharded serving gang (docs/SERVING.md §Sharded serving):
            # one llm.generate job carrying a gang stanza of kind=serving
            # reserves TWO co-located workers all-or-nothing, rendezvouses
            # them into a TP=2 gang, and serves the session set tensor-
            # parallel — rank 0 alone samples and streams, the follower
            # replays the broadcast ragged entries with lm_head DCE'd.
            # While the gang lingers post-job, /api/v1/capacity must show
            # ONE fused row for it (aggregate tokens/s, min-of-ranks page
            # headroom) instead of two independent worker rows, and the
            # fleet metrics must show stream tokens from rank 0 ONLY.
            if not external:
                def _fleet_txt() -> str:
                    return httpx.get(f"{API}/metrics?scope=fleet",
                                     timeout=10.0).text

                motif = [5, 9, 14, 23, 7, 11, 3, 19]
                r = c.post("/api/v1/jobs", json={
                    "topic": "job.tpu.generate",
                    "payload": {"op": "llm.generate",
                                "gang": {"kind": "serving", "workers": 2},
                                "prompts": [motif * 2 + [2]],
                                "max_new_tokens": 12,
                                "cache_pages": 32, "page_size": 8,
                                "linger_s": 20.0}})
                assert r.status_code == 202, r.text
                gang_job = r.json()["job_id"]
                # the fused capacity row appears while the gang is live
                # (the linger window keeps it up past the job result)
                fused, t0 = [], time.time()
                while time.time() - t0 < 90:
                    fused = c.get("/api/v1/capacity").json().get(
                        "serving_gangs", [])
                    if fused:
                        break
                    time.sleep(0.5)
                assert len(fused) == 1, fused
                row = fused[0]
                assert row["size"] == 2 and len(row["members"]) == 2, row
                assert sorted(row["members"].values()) == [0, 1], row
                assert row["leader"] in row["members"], row
                assert row["pages_total_min"] > 0, row
                doc = wait_job(c, gang_job, "SUCCEEDED", 120)
                res = doc["result"]
                assert res["kind"] == "serving", res
                lead = res["per_rank"]["0"]
                follow = res["per_rank"]["1"]
                assert len(lead["results"][0]["tokens"]) == 12, lead
                # one ragged program per rank; the follower replayed every
                # broadcast step and sampled nothing
                assert lead["compiled"] == 1 and follow["compiled"] == 1, res
                assert follow["steps_replayed"] == lead["steps"] > 0, res
                # the gangs table knows the kind (cordumctl gangs)
                gdoc = c.get("/api/v1/gangs").json()
                assert any(g.get("kind") == "serving"
                           for g in gdoc.get("gangs", [])), gdoc
                # rank 0 alone streamed: the stream-token counter carries
                # exactly the rank="0" series
                ranks = set()
                for ln in _fleet_txt().splitlines():
                    if ln.startswith(
                            "cordum_serving_gang_stream_tokens_total{"):
                        ranks.add(ln.split('rank="')[1].split('"')[0])
                assert ranks == {"0"}, ranks
                log(f"15. sharded serving gang: TP=2 gang "
                    f"({'+'.join(sorted(row['members']))}) served the "
                    f"session with 1 ragged program per rank, one fused "
                    f"capacity row ({row['pages_free_min']}/"
                    f"{row['pages_total_min']} min pages free), stream "
                    f"packets from rank 0 only")
            else:
                log("15. sharded serving gang: skipped (external deployment)")

        log("PASS")
        return 0
    finally:
        for p in reversed(procs):
            p.send_signal(signal.SIGTERM)
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
        if not keep:
            log(f"logs kept at {logdir}")


if __name__ == "__main__":
    sys.exit(main())
